"""CycloneFrame / CycloneSeries — the pandas-facade implementation.

Columns are numpy arrays of equal length; the implicit index is positional
(the reference's pandas-on-Spark attaches a distributed default index for
the same reason — frame.py's NATURAL_ORDER_COLUMN — which collapses to row
order here).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


def _narrow_object(out: np.ndarray) -> np.ndarray:
    """Cast an object array to a numeric dtype when every non-null element
    is numeric (None → NaN); ints stay int64 when no nulls, anything mixed
    or stringy keeps object — the dtype-restoring step for values that
    round-tripped through tuples/lists."""
    vals = [x for x in out if x is not None]
    if not vals:
        return out
    if all(isinstance(x, (bool, np.bool_)) for x in vals):
        return out.astype(bool) if len(vals) == len(out) else out
    if all(isinstance(x, (int, np.integer)) for x in vals):
        if len(vals) == len(out):
            return out.astype(np.int64)
        return np.array([np.nan if x is None else float(x) for x in out])
    if all(isinstance(x, (int, float, np.integer, np.floating))
           for x in vals):
        return np.array([np.nan if x is None else float(x) for x in out])
    return out


def _is_null(arr: np.ndarray) -> np.ndarray:
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    if arr.dtype == object:
        return np.array([v is None or (isinstance(v, float) and np.isnan(v))
                         for v in arr], dtype=bool)
    return np.zeros(len(arr), dtype=bool)


class CycloneSeries:
    """1-D labeled column (ref: pyspark/pandas/series.py). ``index`` is an
    optional label array; None means positional (RangeIndex)."""

    def __init__(self, values, name: str = "", index=None):
        self.values = np.asarray(values)
        self.name = name
        self.index = None if index is None else np.asarray(index)

    # -- arithmetic / comparison (elementwise, numpy semantics) ---------------
    def _binop(self, other, op) -> "CycloneSeries":
        if isinstance(other, CycloneSeries):
            if (self.index is not None and other.index is not None
                    and not np.array_equal(self.index, other.index)):
                # label alignment on the index union, NaN where one side is
                # missing — the pandas contract (frame.py align paths)
                union = np.unique(np.concatenate([self.index, other.index]))

                def reindexed(s):
                    pos = {k: i for i, k in enumerate(s.index)}
                    out = np.full(len(union), np.nan)
                    for j, k in enumerate(union):
                        if k in pos:
                            out[j] = s.values[pos[k]]
                    return out

                return CycloneSeries(op(reindexed(self), reindexed(other)),
                                     self.name, index=union)
            rhs = other.values
        else:
            rhs = other
        return CycloneSeries(op(self.values, rhs), self.name,
                             index=self.index)

    def __add__(self, o):
        return self._binop(o, np.add)

    def __sub__(self, o):
        return self._binop(o, np.subtract)

    def __mul__(self, o):
        return self._binop(o, np.multiply)

    def __truediv__(self, o):
        return self._binop(o, np.divide)

    def __eq__(self, o):  # noqa: PYI032 — pandas-style elementwise eq
        return self._binop(o, np.equal)

    def __ne__(self, o):  # noqa: PYI032
        return self._binop(o, np.not_equal)

    def __lt__(self, o):
        return self._binop(o, np.less)

    def __le__(self, o):
        return self._binop(o, np.less_equal)

    def __gt__(self, o):
        return self._binop(o, np.greater)

    def __ge__(self, o):
        return self._binop(o, np.greater_equal)

    def __and__(self, o):
        return self._binop(o, np.logical_and)

    def __or__(self, o):
        return self._binop(o, np.logical_or)

    def __invert__(self):
        return CycloneSeries(np.logical_not(self.values), self.name)

    def __len__(self) -> int:
        return len(self.values)

    def __iter__(self):
        return iter(self.values)

    def __getitem__(self, i):
        return self.values[i]

    # -- reductions ------------------------------------------------------------
    def sum(self):
        return self.values.sum()

    def mean(self):
        return float(np.mean(self.values))

    def std(self):
        n = len(self.values)
        return float(np.std(self.values, ddof=1)) if n > 1 else float("nan")

    def min(self):
        return self.values.min()

    def max(self):
        return self.values.max()

    def count(self) -> int:
        return int((~_is_null(self.values)).sum())

    def nunique(self) -> int:
        return len(np.unique(self.values[~_is_null(self.values)]))

    # -- transforms ------------------------------------------------------------
    def map(self, f: Callable) -> "CycloneSeries":
        return CycloneSeries(np.array([f(v) for v in self.values]), self.name)

    apply = map

    def astype(self, dtype) -> "CycloneSeries":
        return CycloneSeries(self.values.astype(dtype), self.name)

    def isna(self) -> "CycloneSeries":
        return CycloneSeries(_is_null(self.values), self.name)

    def fillna(self, value) -> "CycloneSeries":
        out = self.values.copy()
        out[_is_null(out)] = value
        return CycloneSeries(out, self.name)

    def unique(self) -> np.ndarray:
        seen, out = set(), []
        for v in self.values:
            if v not in seen:
                seen.add(v)
                out.append(v)
        return np.array(out, dtype=self.values.dtype)

    def value_counts(self) -> "CycloneSeries":
        vals, counts = np.unique(self.values, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        s = CycloneSeries(counts[order], self.name)
        s.index = vals[order]
        return s

    def rolling(self, window: int, min_periods: Optional[int] = None
                ) -> "_Rolling":
        return _Rolling(self.values, window,
                        window if min_periods is None else min_periods,
                        self.name, self.index)

    def expanding(self, min_periods: int = 1) -> "_Rolling":
        return _Rolling(self.values, None, min_periods, self.name,
                        self.index)

    @property
    def str(self) -> "_StrAccessor":
        return _StrAccessor(self)

    @property
    def dt(self) -> "_DtAccessor":
        return _DtAccessor(self)

    def to_numpy(self) -> np.ndarray:
        return self.values

    def to_list(self) -> list:
        return self.values.tolist()

    def unstack(self) -> "CycloneFrame":
        """Series with a tuple (MultiIndex) index → frame: the LAST index
        level becomes the columns (ref pandas Series.unstack; NaN where a
        (row, column) pair is absent, ValueError on duplicate pairs)."""
        idx = self.index
        if not (len(idx) and isinstance(idx[0], tuple)):
            raise ValueError("unstack needs a MultiIndex (tuple labels)")
        if len(set(idx)) != len(idx):
            raise ValueError(
                "Index contains duplicate entries, cannot reshape")
        rows = sorted({t[:-1] for t in idx})
        cols = sorted({t[-1] for t in idx})
        data = {c: np.full(len(rows), np.nan) for c in cols}
        rpos = {r: i for i, r in enumerate(rows)}
        for t, v in zip(idx, self.values):
            data[t[-1]][rpos[t[:-1]]] = v
        out = CycloneFrame(data)
        row_labels = [r[0] if len(r) == 1 else r for r in rows]
        out._index = np.array(row_labels, dtype=object)
        names = getattr(self, "index_name", None)
        if isinstance(names, list) and len(names) == len(idx[0]):
            rest = names[:-1]
            out._index_name = rest[0] if len(rest) == 1 else rest
        else:
            out._index_name = "index"
        return out

    def __repr__(self):
        return f"CycloneSeries({self.name!r}, {self.values!r})"


class _Rolling:
    """Rolling (fixed window) / expanding (window=None) aggregations over a
    1-D numeric array — NaN where fewer than ``min_periods`` observations
    exist, matching pandas (ref: pyspark/pandas/window.py Rolling)."""

    def __init__(self, values: np.ndarray, window: Optional[int],
                 min_periods: int, name: str, index):
        self._v = np.asarray(values, dtype=np.float64)
        self._window = window
        self._min = min_periods
        self._name = name
        self._index = index

    def _apply(self, fn) -> CycloneSeries:
        v, n = self._v, len(self._v)
        out = np.full(n, np.nan)
        for i in range(n):
            lo = 0 if self._window is None else max(0, i + 1 - self._window)
            win = v[lo:i + 1]
            win = win[~np.isnan(win)]
            if len(win) >= self._min and len(win):
                out[i] = fn(win)
        return CycloneSeries(out, self._name, index=self._index)

    def sum(self):
        return self._apply(np.sum)

    def mean(self):
        return self._apply(np.mean)

    def min(self):
        return self._apply(np.min)

    def max(self):
        return self._apply(np.max)

    def std(self):
        return self._apply(lambda w: np.std(w, ddof=1)
                           if len(w) > 1 else np.nan)

    def count(self):
        return self._apply(len)


class _FrameRolling:
    """Column-wise rolling over a frame's numeric columns."""

    def __init__(self, frame: "CycloneFrame", window, min_periods):
        self._frame = frame
        self._window = window
        self._min = min_periods

    def _apply(self, op: str) -> "CycloneFrame":
        out = {}
        for k, v in self._frame._cols.items():
            if v.dtype.kind in "if":
                r = _Rolling(v, self._window,
                             self._min if self._min is not None
                             else (self._window or 1), k, None)
                out[k] = getattr(r, op)().values
        return self._frame._like(out)

    def sum(self):
        return self._apply("sum")

    def mean(self):
        return self._apply("mean")

    def min(self):
        return self._apply("min")

    def max(self):
        return self._apply("max")

    def std(self):
        return self._apply("std")


class _StrAccessor:
    """Vectorized string methods (ref: pyspark/pandas/strings.py)."""

    def __init__(self, s: CycloneSeries):
        self._s = s

    def _map(self, f, dtype=object) -> CycloneSeries:
        vals = [None if v is None else f(v) for v in self._s.values]
        if dtype is not object and any(v is None for v in vals):
            # pandas propagates nulls as NaN rather than failing the cast:
            # len() -> float64 with NaN, boolean tests -> object with NaN
            vals = [np.nan if v is None else v for v in vals]
            dtype = np.float64 if dtype is np.int64 else object
        return CycloneSeries(np.array(vals, dtype=dtype), self._s.name,
                             index=self._s.index)

    def lower(self):
        return self._map(str.lower)

    def upper(self):
        return self._map(str.upper)

    def strip(self):
        return self._map(str.strip)

    def len(self):
        return self._map(len, dtype=np.int64)

    def contains(self, pat: str, regex: bool = True):
        import re
        if regex:
            rx = re.compile(pat)
            return self._map(lambda v: rx.search(v) is not None, dtype=bool)
        return self._map(lambda v: pat in v, dtype=bool)

    def startswith(self, pat: str):
        return self._map(lambda v: v.startswith(pat), dtype=bool)

    def endswith(self, pat: str):
        return self._map(lambda v: v.endswith(pat), dtype=bool)

    def replace(self, pat: str, repl: str, regex: bool = True):
        import re
        if regex:
            rx = re.compile(pat)
            return self._map(lambda v: rx.sub(repl, v))
        return self._map(lambda v: v.replace(pat, repl))

    def slice(self, start=None, stop=None, step=None):
        return self._map(lambda v: v[start:stop:step])

    def split(self, pat: str = " "):
        return self._map(lambda v: v.split(pat))

    def cat(self, sep: str = "") -> str:
        return sep.join(v for v in self._s.values if v is not None)


class _DtAccessor:
    """Datetime component accessors over datetime64 columns (ref:
    pyspark/pandas/datetimes.py)."""

    def __init__(self, s: CycloneSeries):
        self._v = np.asarray(s.values, dtype="datetime64[s]")
        self._name = s.name
        self._index = s.index

    def _series(self, vals, dtype=np.int64) -> CycloneSeries:
        return CycloneSeries(np.asarray(vals, dtype=dtype), self._name,
                             index=self._index)

    @property
    def year(self):
        return self._series(self._v.astype("M8[Y]").astype(np.int64) + 1970)

    @property
    def month(self):
        return self._series(
            self._v.astype("M8[M]").astype(np.int64) % 12 + 1)

    @property
    def day(self):
        return self._series((self._v.astype("M8[D]")
                             - self._v.astype("M8[M]").astype("M8[D]"))
                            .astype(np.int64) + 1)

    @property
    def hour(self):
        return self._series((self._v.astype("M8[h]")
                             - self._v.astype("M8[D]").astype("M8[h]"))
                            .astype(np.int64))

    @property
    def minute(self):
        return self._series((self._v.astype("M8[m]")
                             - self._v.astype("M8[h]").astype("M8[m]"))
                            .astype(np.int64))

    @property
    def second(self):
        return self._series((self._v.astype("M8[s]")
                             - self._v.astype("M8[m]").astype("M8[s]"))
                            .astype(np.int64))

    @property
    def dayofweek(self):
        # 1970-01-01 is a Thursday = 3 under pandas' Monday=0 convention
        return self._series(
            (self._v.astype("M8[D]").astype(np.int64) + 3) % 7)

    @property
    def date(self):
        return CycloneSeries(self._v.astype("M8[D]"), self._name,
                             index=self._index)


class _LocIndexer:
    """Label-based row access (ref: pyspark/pandas/indexing.py loc)."""

    def __init__(self, frame: "CycloneFrame"):
        self._f = frame

    def __getitem__(self, key):
        f = self._f
        idx = f.index
        if (isinstance(f._index_name, list) and isinstance(key, tuple)
                and len(key) == len(f._index_name)):
            # MultiIndex label lookup: a full tuple addresses one label
            # (takes precedence over the (rows, cols) reading, as pandas';
            # no match falls THROUGH so loc[(label_tuple), col] still works)
            pos = np.array([i for i, t in enumerate(idx) if t == key],
                           dtype=np.int64)
            if len(pos) == 1:
                return {c: f._cols[c][pos[0]] for c in f.columns}
            if len(pos):
                return f._take(pos)
        if isinstance(key, tuple) and len(key) == 2:
            rows, cols = key
            sub = self[rows]
            if isinstance(sub, dict):  # unique row label -> row mapping
                if isinstance(cols, str):
                    return sub[cols]
                return {c: sub[c] for c in cols}
            if isinstance(cols, str):
                return sub[cols]
            return sub[list(cols)]
        if isinstance(key, CycloneSeries):  # boolean mask
            return f[key]
        if isinstance(key, slice):
            # label slices are INCLUSIVE on both ends in pandas; on a
            # monotonic index a missing bound slices to its insertion
            # point, otherwise it is KeyError; duplicate bound labels on a
            # non-monotonic index are rejected (pandas contract)
            try:
                inc = bool(np.all(idx[:-1] <= idx[1:]))
                dec = not inc and bool(np.all(idx[:-1] >= idx[1:]))
            except TypeError:  # unorderable mixed-type labels
                inc = dec = False
            rev = idx[::-1] if dec else None

            def _bound(label, side):
                hits = np.nonzero(idx == label)[0]
                if len(hits) > 1 and not (inc or dec):
                    raise KeyError(
                        f"Cannot get {side} slice bound for non-unique "
                        f"label: {label!r}")
                if len(hits):
                    return int(hits[0] if side == "left" else hits[-1])
                if inc:
                    p = int(np.searchsorted(
                        idx, label, side="left" if side == "left" else "right"))
                    return p if side == "left" else p - 1
                if dec:
                    p = int(np.searchsorted(
                        rev, label, side="right" if side == "left" else "left"))
                    return (len(f) - p) if side == "left" else len(f) - p - 1
                raise KeyError(label)
            lo = 0 if key.start is None else _bound(key.start, "left")
            hi = (len(f) - 1 if key.stop is None
                  else _bound(key.stop, "right"))
            return f._take(np.arange(lo, hi + 1))
        if isinstance(key, (list, np.ndarray)):
            # every row matching each label, label order outer (pandas
            # duplicate-label semantics). Tuple labels (MultiIndex) compare
            # elementwise — numpy would broadcast a tuple against the index
            pos = []
            for k in key:
                if isinstance(k, tuple):
                    hits = np.array([i for i, t in enumerate(idx) if t == k],
                                    dtype=np.int64)
                else:
                    hits = np.nonzero(idx == k)[0]
                if not len(hits):
                    raise KeyError(k)
                pos.extend(hits)
            return f._take(np.array(pos, dtype=np.int64))
        pos = np.nonzero(idx == key)[0]
        if not len(pos):
            raise KeyError(key)
        if len(pos) == 1:
            return {c: f._cols[c][pos[0]] for c in f.columns}
        return f._take(pos)


class _ILocIndexer:
    """Position-based row access."""

    def __init__(self, frame: "CycloneFrame"):
        self._f = frame

    def __getitem__(self, key):
        f = self._f
        if isinstance(key, int):
            n = len(f)
            if key < 0:
                key += n
            if not 0 <= key < n:
                raise IndexError(key)
            return {c: f._cols[c][key] for c in f.columns}
        if isinstance(key, slice):
            return f._take(np.arange(len(f))[key])
        return f._take(np.asarray(key))


class _GroupBy:
    """(ref: pyspark/pandas/groupby.py) — delegates to the SQL aggregate."""

    def __init__(self, frame: "CycloneFrame", keys: List[str]):
        self._frame = frame
        self._keys = keys

    def _agg(self, fns: Dict[str, str], suffix: bool) -> "CycloneFrame":
        from cycloneml_tpu.sql import functions as F
        from cycloneml_tpu.sql.session import CycloneSession
        df = CycloneSession().create_data_frame(
            {k: v for k, v in self._frame._cols.items()})
        agg_cols = []
        for col, fn in fns.items():
            fobj = {"sum": F.sum, "mean": F.avg, "avg": F.avg, "min": F.min,
                    "max": F.max, "count": F.count}[fn]
            agg_cols.append(fobj(col).alias(f"{col}_{fn}" if suffix else col))
        out = df.group_by(*self._keys).agg(*agg_cols).to_dict()
        return CycloneFrame(out)

    def agg(self, spec: Dict[str, str]) -> "CycloneFrame":
        return self._agg(spec, suffix=True)

    def _all_numeric(self, fn: str) -> "CycloneFrame":
        cols = {c: fn for c in self._frame.columns
                if c not in self._keys
                and self._frame._cols[c].dtype != object}
        # plain pandas naming: df.groupby(k).sum() keeps column names
        return self._agg(cols, suffix=False)

    def sum(self):
        return self._all_numeric("sum")

    def mean(self):
        return self._all_numeric("mean")

    def min(self):
        return self._all_numeric("min")

    def max(self):
        return self._all_numeric("max")

    def count(self):
        rest = [c for c in self._frame.columns if c not in self._keys]
        return self._agg({c: "count" for c in rest}, suffix=False)

    def apply(self, func) -> Union["CycloneSeries", "CycloneFrame"]:
        """(ref pandas groupby.apply / pyspark.pandas groupby.py apply):
        call ``func`` on each group's sub-frame, groups in sorted key
        order. Scalar results → a Series indexed by group key; Series
        results → a frame (one row per group, index = group key)."""
        f = self._frame
        key_tuples = list(zip(*[f._cols[k] for k in self._keys]))
        order = {}
        for i, t in enumerate(key_tuples):
            order.setdefault(t, []).append(i)
        results = []
        labels = []
        for t in sorted(order):
            pos = np.asarray(order[t], dtype=np.int64)
            sub = f._take(pos)
            results.append(func(sub))
            labels.append(t[0] if len(self._keys) == 1 else t)
        label_arr = np.array(labels, dtype=object)
        name = (self._keys[0] if len(self._keys) == 1
                else list(self._keys))
        if all(isinstance(r, CycloneSeries) for r in results):
            cols = list(results[0].index)
            out = CycloneFrame({c: _narrow_object(np.array(
                [r.values[list(r.index).index(c)] for r in results],
                dtype=object)) for c in cols})
            out._index = label_arr
            out._index_name = name
            return out
        out_s = CycloneSeries(_narrow_object(np.array(results, dtype=object)),
                              None, index=label_arr)
        return out_s


def _astype_pandas(arr: np.ndarray, dtype) -> np.ndarray:
    """One column cast with pandas semantics (ref pyspark/pandas/
    data_type_ops): float NaN/inf -> integer raises; object parses
    per-element; str stringifies everything (NaN -> 'nan')."""
    arr = np.asarray(arr)
    dt = np.dtype(dtype) if dtype not in (str, "str", "string") else None
    if dt is None or dt.kind in "US":
        out = np.empty(len(arr), dtype=object)
        null = _is_null(arr)
        for i, v in enumerate(arr):
            out[i] = v if null[i] else str(v)  # NaN survives str cast
        return out
    if dt.kind in "iu":
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            raise ValueError(
                "Cannot convert non-finite values (NA or inf) to integer")
        if arr.dtype == object:
            return np.array([int(v) for v in arr], dtype=dt)
        return arr.astype(dt)
    if dt.kind == "f" and arr.dtype == object:
        return np.array([np.nan if v is None else float(v) for v in arr],
                        dtype=dt)
    return arr.astype(dt)


# both freq alias generations: pandas<2.2 ("H","T","M","S") and >=2.2
# ("h","min","ME","s") spell the same rules
_FREQ_UNIT = {"S": "s", "T": "m", "MIN": "m", "H": "h", "D": "D",
              "W": "W", "M": "M", "ME": "M"}


def _parse_freq(freq: str):
    """'15T' -> (15, 'm'); bare letters default to multiplier 1."""
    i = 0
    while i < len(freq) and freq[i].isdigit():
        i += 1
    mult = int(freq[:i]) if i else 1
    unit = _FREQ_UNIT.get(freq[i:].upper())
    if unit is None:
        raise ValueError(f"unsupported freq {freq!r}")
    return mult, unit


def date_range(start=None, end=None, periods: Optional[int] = None,
               freq: str = "D") -> np.ndarray:
    """(ref pandas.date_range) — datetime64[ns] range from any two of
    start/end/periods. Calendar rules: W anchors on Sundays, M emits
    month ENDS, like pandas."""
    mult, unit = _parse_freq(freq)
    if start is None:
        if end is None or periods is None:
            raise ValueError(
                "date_range needs two of start/end/periods")
        if unit == "M":
            # anchor on the last month END on or before ``end``
            e_day = np.datetime64(end, "D")
            em = np.datetime64(end, "M")
            eom = (em + np.timedelta64(1, "M")).astype("M8[D]") \
                - np.timedelta64(1, "D")
            if eom > e_day:
                em = em - np.timedelta64(1, "M")
            months = em - np.arange(periods - 1, -1, -1) \
                * np.timedelta64(mult, "M")
            ends = (months + np.timedelta64(1, "M")).astype("M8[D]") \
                - np.timedelta64(1, "D")
            return ends.astype("M8[ns]")
        if unit == "W":
            e = np.datetime64(end, "D")
            dow = (e.astype(np.int64) + 3) % 7  # Mon=0
            last = e - np.timedelta64((int(dow) - 6) % 7, "D")
            step = np.timedelta64(7 * mult, "D")
            return (last - np.arange(periods - 1, -1, -1) * step
                    ).astype("M8[ns]")
        step = np.timedelta64(mult, unit)
        e = np.datetime64(end).astype("M8[ns]")
        return (e - np.arange(periods - 1, -1, -1) * step).astype("M8[ns]")
    if unit == "M":
        # month-end stamps: walk month starts, step back one day
        s = np.datetime64(start, "M")
        if periods is None:
            e = np.datetime64(end, "M")
            months = np.arange(s, e + np.timedelta64(1, "M"),
                               np.timedelta64(mult, "M"))
        else:
            months = s + np.arange(periods) * np.timedelta64(mult, "M")
        ends = (months + np.timedelta64(1, "M")).astype("M8[D]") \
            - np.timedelta64(1, "D")
        if end is not None and periods is None:
            ends = ends[ends <= np.datetime64(end, "D")]
        return ends.astype("M8[ns]")
    if unit == "W":
        # anchor each stamp on the Sunday >= start (pandas W = W-SUN)
        s = np.datetime64(start, "D")
        dow = (s.astype(np.int64) + 3) % 7  # Mon=0; 1970-01-01 Thursday=3
        first = s + np.timedelta64((6 - int(dow)) % 7, "D")
        step = np.timedelta64(7 * mult, "D")
        if periods is None:
            e = np.datetime64(end, "D")
            out = np.arange(first, e + np.timedelta64(1, "D"), step)
        else:
            out = first + np.arange(periods) * step
        return out.astype("M8[ns]")
    step = np.timedelta64(mult, unit)
    if periods is not None:
        s = np.datetime64(start).astype("M8[ns]")
        return (s + np.arange(periods) * step).astype("M8[ns]")
    s = np.datetime64(start).astype("M8[ns]")
    e = np.datetime64(end).astype("M8[ns]")
    return np.arange(s, e + np.timedelta64(1, "ns"), step).astype("M8[ns]")


class _Resampler:
    """Bucket rows by a floored/anchored datetime key and aggregate;
    empty bins materialize like pandas' resample output."""

    def __init__(self, ts: np.ndarray, cols: Dict[str, np.ndarray],
                 rule: str, index_name: str):
        self._ts = ts
        self._cols = cols
        self._rule = rule
        self._index_name = index_name

    def _bins(self):
        mult, unit = _parse_freq(self._rule)
        ts = self._ts
        if unit == "M":
            months = ts.astype("M8[M]")
            labels = ((months + np.timedelta64(1, "M")).astype("M8[D]")
                      - np.timedelta64(1, "D")).astype("M8[ns]")
            lo, hi = months.min(), months.max()
            all_m = np.arange(lo, hi + np.timedelta64(1, "M"))
            full = ((all_m + np.timedelta64(1, "M")).astype("M8[D]")
                    - np.timedelta64(1, "D")).astype("M8[ns]")
            return labels, full
        if unit == "W":
            days = ts.astype("M8[D]")
            dow = (days.astype(np.int64) + 3) % 7  # Mon=0
            labels = (days + ((6 - dow) % 7).astype("m8[D]")
                      ).astype("M8[ns]")
            full = np.arange(labels.min(), labels.max()
                             + np.timedelta64(1, "ns"),
                             np.timedelta64(7, "D").astype("m8[ns]"))
            return labels, full
        step = np.timedelta64(mult, unit).astype("m8[ns]")
        base = ts.astype(f"M8[{unit}]").astype("M8[ns]")
        if mult != 1:
            # pandas origin="start_day": bins anchor at the first
            # timestamp's MIDNIGHT, not at the first timestamp itself
            origin = ts.min().astype("M8[D]").astype("M8[ns]")
            base = origin + ((base - origin) // step) * step
        full = np.arange(base.min(), base.max() + np.timedelta64(1, "ns"),
                         step)
        return base, full

    def _agg(self, fn: str) -> "CycloneFrame":
        labels, full = self._bins()
        pos = {v: i for i, v in enumerate(full)}
        codes = np.array([pos[v] for v in labels], dtype=np.int64)
        n = len(full)
        out: Dict[str, np.ndarray] = {}
        for k, v in self._cols.items():
            v = np.asarray(v)
            if v.dtype == object:
                continue
            v = v.astype(np.float64)
            ok = ~np.isnan(v)  # pandas skipna: NaN rows leave their bin
            vc, cc = v[ok], codes[ok]
            csum = np.bincount(cc, weights=vc, minlength=n)
            cnt = np.bincount(cc, minlength=n).astype(np.float64)
            if fn == "sum":
                res = csum
            elif fn == "count":
                res = cnt
            elif fn == "mean":
                with np.errstate(invalid="ignore"):
                    res = csum / cnt
            else:  # min/max: empty bins -> NaN
                op = np.minimum if fn == "min" else np.maximum
                res_tmp = np.full(n, np.inf if fn == "min" else -np.inf)
                op.at(res_tmp, cc, vc)
                res = np.where(cnt > 0, res_tmp, np.nan)
            out[k] = res.astype(np.int64) if fn == "count" else res
        frame = CycloneFrame(out)
        frame._index = full
        frame._index_name = self._index_name
        return frame

    def sum(self):
        return self._agg("sum")

    def mean(self):
        return self._agg("mean")

    def count(self):
        return self._agg("count")

    def min(self):
        return self._agg("min")

    def max(self):
        return self._agg("max")


class CycloneFrame:
    """2-D table (ref: pyspark/pandas/frame.py)."""

    def __init__(self, data: Union[Dict[str, Any], "CycloneFrame"]):
        self._index: Optional[np.ndarray] = None  # None = positional
        self._index_name: str = "index"
        if isinstance(data, CycloneFrame):
            self._cols = {k: v.copy() for k, v in data._cols.items()}
            self._index = (None if data._index is None
                           else data._index.copy())
            self._index_name = data._index_name
            return
        cols = {}
        n = None
        for k, v in data.items():
            arr = v.values if isinstance(v, CycloneSeries) else np.asarray(v)
            if arr.dtype.kind in "US":
                arr = arr.astype(object)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(f"column {k!r}: length {len(arr)} != {n}")
            cols[k] = arr
        self._cols = cols

    # -- index ----------------------------------------------------------------
    @property
    def index(self) -> np.ndarray:
        return (np.arange(len(self)) if self._index is None
                else self._index)

    def set_index(self, col) -> "CycloneFrame":
        """(ref pandas set_index) — the column(s) become the row-label
        index and leave the data columns. A LIST of columns builds a
        MultiIndex analog: the index holds per-row label TUPLES and the
        index name is the level-name list (ref pyspark/pandas/indexes/
        multi.py — tuple-labelled rows over the same frame machinery)."""
        cols = [col] if isinstance(col, str) else list(col)
        out = CycloneFrame({k: v for k, v in self._cols.items()
                            if k not in cols})
        if len(cols) == 1:
            out._index = np.asarray(self._cols[cols[0]])
            out._index_name = cols[0]
        else:
            idx = np.empty(len(self), dtype=object)
            for i in range(len(self)):
                idx[i] = tuple(self._cols[c][i] for c in cols)
            out._index = idx
            out._index_name = list(cols)
        return out

    def reset_index(self, drop: bool = False) -> "CycloneFrame":
        cols: Dict[str, Any] = {}
        if not drop and self._index is not None:
            if isinstance(self._index_name, list):
                # MultiIndex: expand the label tuples back into columns
                for j, nm in enumerate(self._index_name):
                    cols[nm] = _narrow_object(np.array(
                        [t[j] for t in self._index], dtype=object))
            else:
                cols[self._index_name] = self._index
        cols.update(self._cols)
        return CycloneFrame(cols)

    def _like(self, cols: Dict[str, np.ndarray]) -> "CycloneFrame":
        """A frame with these columns and THIS frame's index metadata."""
        out = CycloneFrame(cols)
        out._index = self._index
        out._index_name = self._index_name
        return out

    def _take(self, pos: np.ndarray) -> "CycloneFrame":
        """Row subset by position, index carried along."""
        out = CycloneFrame({k: v[pos] for k, v in self._cols.items()})
        if self._index is not None:
            out._index = self._index[pos]
            out._index_name = self._index_name
        return out

    @property
    def loc(self) -> _LocIndexer:
        return _LocIndexer(self)

    @property
    def iloc(self) -> _ILocIndexer:
        return _ILocIndexer(self)

    def rolling(self, window: int,
                min_periods: Optional[int] = None) -> _FrameRolling:
        return _FrameRolling(self, window, min_periods)

    def expanding(self, min_periods: int = 1) -> _FrameRolling:
        return _FrameRolling(self, None, min_periods)

    # -- metadata --------------------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    @property
    def shape(self):
        n = len(next(iter(self._cols.values()))) if self._cols else 0
        return (n, len(self._cols))

    @property
    def dtypes(self) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def __len__(self) -> int:
        return self.shape[0]

    # -- selection -------------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, str) or (np.isscalar(key) and key in self._cols):
            # (scalar non-string column labels come from unstack's levels)
            s = CycloneSeries(self._cols[key], key, index=self._index)
            s.index_name = self._index_name  # unstack needs the level names
            return s
        if isinstance(key, list):
            return self._like({k: self._cols[k] for k in key})
        if isinstance(key, CycloneSeries):  # boolean mask
            vals = np.asarray(key.values)
            has_null = (
                any(v is None or (isinstance(v, float) and np.isnan(v))
                    for v in vals)
                if vals.dtype == object
                else vals.dtype.kind == "f" and bool(np.isnan(vals).any()))
            if has_null:
                # pandas contract: a mask with nulls is an error, never a
                # silent truthy-NaN selection (NaN casts to True)
                raise ValueError(
                    "Cannot mask with non-boolean array containing NA / "
                    "NaN values")
            mask = vals.astype(bool)
            return self._take(np.nonzero(mask)[0])
        raise TypeError(f"cannot index with {type(key).__name__}")

    def __setitem__(self, key: str, value) -> None:
        arr = value.values if isinstance(value, CycloneSeries) else value
        arr = np.asarray(arr)
        if arr.ndim == 0:
            arr = np.full(len(self), arr[()])
        if self._cols and len(arr) != len(self):
            raise ValueError(
                f"column {key!r}: length {len(arr)} != {len(self)}")
        self._cols[key] = arr

    def assign(self, **kw) -> "CycloneFrame":
        out = CycloneFrame(self)
        for k, v in kw.items():
            out[k] = v(out) if callable(v) else v
        return out

    def drop(self, columns: Sequence[str]) -> "CycloneFrame":
        drop = set([columns] if isinstance(columns, str) else columns)
        return CycloneFrame({k: v for k, v in self._cols.items()
                             if k not in drop})

    def rename(self, columns: Dict[str, str]) -> "CycloneFrame":
        return CycloneFrame({columns.get(k, k): v
                             for k, v in self._cols.items()})

    # -- rows ------------------------------------------------------------------
    def head(self, n: int = 5) -> "CycloneFrame":
        # pandas semantics: negative n means "all but the last |n| rows"
        return self._take(np.arange(len(self))[:n])

    def tail(self, n: int = 5) -> "CycloneFrame":
        total = np.arange(len(self))
        return self._take(total[-n:] if n else total[:0])

    def sort_values(self, by, ascending: bool = True) -> "CycloneFrame":
        keys = [by] if isinstance(by, str) else list(by)
        order = np.lexsort([self._cols[k] for k in reversed(keys)])
        if not ascending:
            order = order[::-1]
        return self._take(order)

    def sort_index(self, ascending: bool = True) -> "CycloneFrame":
        order = np.argsort(self.index, kind="stable")
        if not ascending:
            order = order[::-1]
        return self._take(order)

    # -- missing data ----------------------------------------------------------
    def isna(self) -> "CycloneFrame":
        return CycloneFrame({k: _is_null(v) for k, v in self._cols.items()})

    def fillna(self, value) -> "CycloneFrame":
        return CycloneFrame({k: CycloneSeries(v).fillna(value).values
                             for k, v in self._cols.items()})

    def dropna(self) -> "CycloneFrame":
        if not self._cols:
            return CycloneFrame({})
        keep = ~np.logical_or.reduce([_is_null(v)
                                      for v in self._cols.values()])
        return self._take(np.nonzero(keep)[0])

    # -- combine ---------------------------------------------------------------
    def merge(self, other: "CycloneFrame", on=None, how: str = "inner",
              validate: Optional[str] = None, indicator: bool = False,
              left_on=None, right_on=None, left_index: bool = False,
              right_index: bool = False) -> "CycloneFrame":
        if left_index or right_index or left_on or right_on:
            # merge-on-index (ref pandas left_index/right_index and
            # pyspark.pandas frame.py merge): materialize each side's key
            # — index or named column — under a shared temp name, run the
            # column merge, then restore pandas' result-index rule (the
            # joined key labels the rows when an index participates)
            if on is not None:
                raise ValueError(
                    'Can only pass argument "on" OR index/left_on/'
                    "right_on combinations")
            key = "__cyclone_mkey"
            prov = "__cyclone_prov"
            lf = CycloneFrame(dict(self._cols))
            rf = CycloneFrame(dict(other._cols))
            if left_index:
                lf._cols = {key: np.asarray(self.index), **lf._cols}
            else:
                if left_on is None:
                    raise ValueError("must pass left_on or left_index")
                lf._cols = {key: lf._cols[left_on], **lf._cols}
                # pandas rule for a mixed merge: the COLUMN side's index
                # labels the result rows — carry it through the join
                lf._cols[prov] = np.asarray(self.index, dtype=object)
            if right_index:
                rf._cols = {key: np.asarray(other.index), **rf._cols}
            else:
                if right_on is None:
                    raise ValueError("must pass right_on or right_index")
                rf._cols = {key: rf._cols[right_on], **rf._cols}
                if prov not in lf._cols:
                    rf._cols[prov] = np.asarray(other.index, dtype=object)
            merged = lf.merge(rf, on=key, how=how, validate=validate,
                              indicator=indicator)
            labels = merged._cols.pop(key)
            carried = merged._cols.pop(prov, None)
            if left_index and right_index:
                merged._index = labels
                merged._index_name = (self._index_name
                                      if self._index is not None else
                                      other._index_name)
            else:
                # mixed: the column side's carried labels; rows that only
                # the INDEX side produced (outer/right unmatched) fall
                # back to the join-key label, which is all pandas has for
                # them either
                vals = np.asarray(carried)
                null = np.array([x is None or (isinstance(x, float)
                                               and np.isnan(x))
                                 for x in vals], dtype=bool)
                merged._index = _narrow_object(
                    np.where(null, labels.astype(object), vals))
                merged._index_name = (other._index_name if left_index
                                      else self._index_name)
            return merged
        from cycloneml_tpu.sql.session import CycloneSession
        keys = [on] if isinstance(on, str) else list(on)
        if validate is not None:
            # (ref pandas merge validate=): check key uniqueness per side
            # BEFORE joining; MergeError semantics via ValueError
            v = {"one_to_one": "1:1", "one_to_many": "1:m",
                 "many_to_one": "m:1", "many_to_many": "m:m"}.get(
                     validate, validate)
            if v not in ("1:1", "1:m", "m:1", "m:m"):
                raise ValueError(f"not a valid argument for validate: "
                                 f"{validate!r}")

            def _unique(frame):
                seen = set()
                for t in zip(*[frame._cols[k] for k in keys]):
                    if t in seen:
                        return False
                    seen.add(t)
                return True
            if v in ("1:1", "1:m") and not _unique(self):
                raise ValueError(
                    "Merge keys are not unique in left dataset; not a "
                    f"{validate} merge")
            if v in ("1:1", "m:1") and not _unique(other):
                raise ValueError(
                    "Merge keys are not unique in right dataset; not a "
                    f"{validate} merge")
        s = CycloneSession()
        lcols = dict(self._cols)
        rcols = dict(other._cols)
        if indicator:
            # provenance markers ride the join; NaN-ness afterwards says
            # which side produced each row (ref pandas indicator=True)
            lcols["__cyclone_lm"] = np.ones(len(self))
            rcols["__cyclone_rm"] = np.ones(len(other))
        left = s.create_data_frame(lcols)
        right = s.create_data_frame(rcols)
        out = left.join(right, on=on, how=how).to_dict()
        if indicator:
            lm = np.asarray(out.pop("__cyclone_lm"), dtype=np.float64)
            rm = np.asarray(out.pop("__cyclone_rm"), dtype=np.float64)
            out["_merge"] = np.where(
                np.isnan(lm), "right_only",
                np.where(np.isnan(rm), "left_only", "both")).astype(object)
        return CycloneFrame(out)

    def groupby(self, by) -> _GroupBy:
        return _GroupBy(self, [by] if isinstance(by, str) else list(by))

    # -- dtypes (ref pandas astype semantics; pyspark/pandas/data_type_ops)
    def astype(self, dtype) -> "CycloneFrame":
        """Single dtype or {column: dtype}; pandas cast rules — float
        NaN/inf to integer RAISES, object numeric strings parse, any
        value stringifies under str (NaN -> 'nan')."""
        spec = dtype if isinstance(dtype, dict) else {
            k: dtype for k in self._cols}
        cols = dict(self._cols)
        for k, dt in spec.items():
            cols[k] = _astype_pandas(cols[k], dt)
        return self._like(cols)

    # -- iteration protocols (ref pandas iterrows/itertuples) ------------
    def iterrows(self):
        """Yields ``(index_label, row Series)`` — the row rides as a
        Series over the column names, like pandas (and like pandas, this
        is the slow path; prefer columnar ops)."""
        labels = self.index
        names = list(self._cols)
        col_vals = [self._cols[c] for c in names]
        for i in range(len(self)):
            row = np.empty(len(names), dtype=object)
            for j, v in enumerate(col_vals):
                row[j] = v[i]
            yield labels[i], CycloneSeries(row, name=str(labels[i]),
                                           index=names)

    def itertuples(self, index: bool = True, name: str = "Cyclone"):
        """Yields namedtuples (positionally equal to pandas' — tuple
        comparison ignores the class name); invalid/duplicate field
        names fall back to positional via rename=True, as pandas does."""
        import collections
        names = list(self._cols)
        fields = (["Index"] if index else []) + names
        tup = collections.namedtuple(name, fields, rename=True)
        labels = self.index
        col_vals = [self._cols[c] for c in names]
        for i in range(len(self)):
            vals = [v[i] for v in col_vals]
            yield tup(*([labels[i]] + vals if index else vals))

    # -- resample (ref pandas resample; basic calendar rules) ------------
    def resample(self, rule: str, on: Optional[str] = None) -> "_Resampler":
        """Downsample over a datetime64 index (or the ``on`` column):
        supports the S/T(min)/H/D/W/M rules with multipliers. Like
        pandas, EMPTY bins appear in the result (sum/count 0, mean/min/
        max NaN)."""
        ts = (np.asarray(self._cols[on]) if on is not None
              else np.asarray(self.index))
        if ts.dtype.kind != "M":
            ts = ts.astype("M8[ns]")
        data_cols = {k: v for k, v in self._cols.items() if k != on}
        return _Resampler(ts.astype("M8[ns]"), data_cols, rule,
                          self._index_name if on is None else (on or
                                                               "index"))

    # -- stats -----------------------------------------------------------------
    def describe(self) -> "CycloneFrame":
        stats = ["count", "mean", "std", "min", "max"]
        out: Dict[str, list] = {"summary": stats}
        for k, v in self._cols.items():
            if v.dtype == object:
                continue
            s = CycloneSeries(v)
            out[k] = [s.count(), s.mean(), s.std(), s.min(), s.max()]
        return CycloneFrame({k: np.asarray(v, dtype=object)
                             if k == "summary" else np.asarray(v, dtype=float)
                             for k, v in out.items()})

    def apply(self, f: Callable, axis: int = 0):
        if axis == 0:
            return CycloneFrame({k: np.asarray(f(CycloneSeries(v, k)))
                                 for k, v in self._cols.items()})
        rows = self.to_records()
        return CycloneSeries(np.array([f(r) for r in rows]))

    # -- bridges ---------------------------------------------------------------
    def to_records(self) -> List[Dict[str, Any]]:
        cols = self.columns
        return [{c: self._cols[c][i] for c in cols}
                for i in range(len(self))]

    def to_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._cols)

    def to_pandas(self):
        import pandas as pd
        pdf = pd.DataFrame({k: v for k, v in self._cols.items()})
        if self._index is not None:
            if isinstance(self._index_name, list):
                pdf.index = pd.MultiIndex.from_tuples(
                    list(self._index), names=self._index_name)
            else:
                pdf.index = pd.Index(self._index, name=self._index_name)
        return pdf

    @classmethod
    def from_pandas(cls, pdf) -> "CycloneFrame":
        return cls({c: pdf[c].to_numpy() for c in pdf.columns})

    def to_sql_df(self, session=None):
        from cycloneml_tpu.sql.session import CycloneSession
        return (session or CycloneSession()).create_data_frame(
            dict(self._cols))

    def __repr__(self):
        n, m = self.shape
        return f"CycloneFrame({n} rows x {m} cols: {self.columns})"


def read_csv(path: str, header: bool = True,
             delimiter: str = ",") -> CycloneFrame:
    from cycloneml_tpu.sql.session import CycloneSession
    return CycloneFrame(
        CycloneSession().read_csv(path, header, delimiter).to_dict())


def concat(frames: Sequence[CycloneFrame], axis: int = 0,
           ignore_index: bool = False) -> CycloneFrame:
    """(ref pandas concat) — axis=0 stacks rows over the column UNION
    (missing columns fill NaN/None); axis=1 joins columns positionally."""
    frames = list(frames)
    if not frames:
        return CycloneFrame({})
    if axis == 1:
        cols: Dict[str, np.ndarray] = {}
        for f in frames:
            for k, v in f._cols.items():
                name = k
                i = 1
                while name in cols:  # pandas keeps duplicates; we suffix
                    name = f"{k}_{i}"
                    i += 1
                cols[name] = v
        return CycloneFrame(cols)
    names: List[str] = []
    for f in frames:
        for k in f.columns:
            if k not in names:
                names.append(k)
    out: Dict[str, np.ndarray] = {}
    for k in names:
        parts = []
        for f in frames:
            if k in f._cols:
                parts.append(np.asarray(f._cols[k], dtype=object)
                             if any(k not in g._cols for g in frames)
                             else f._cols[k])
            else:
                parts.append(np.full(len(f), None, dtype=object))
        out[k] = np.concatenate(parts)
    res = CycloneFrame(out)
    if not ignore_index:
        res._index = np.concatenate([f.index for f in frames])
    return res


def pivot_table(frame: CycloneFrame, values: str, index: str, columns: str,
                aggfunc: str = "mean", margins: bool = False,
                margins_name: str = "All") -> CycloneFrame:
    """(ref pandas pivot_table / pyspark/pandas/frame.py pivot_table) — one
    output row per distinct ``index`` value, one column per distinct
    ``columns`` value, cells aggregated with ``aggfunc``.

    ``margins=True`` appends an ``All`` column (per-row aggregate over the
    raw records) and an ``All`` row (per-column aggregate), aggregated
    over the UNDERLYING rows — not over cell results — matching pandas."""
    if aggfunc not in ("mean", "sum", "min", "max", "count"):
        raise ValueError(f"unsupported aggfunc {aggfunc!r}")
    iv = np.asarray(frame._cols[index])
    cv = np.asarray(frame._cols[columns])
    vv = np.asarray(frame._cols[values], dtype=np.float64)
    # one factorized pass: flat group id = row_code * n_cols + col_code
    # (a per-cell boolean mask scan is O(rows * cells))
    rows, r_code = np.unique(iv, return_inverse=True)
    cols, c_code = np.unique(cv, return_inverse=True)
    n_cells = len(rows) * len(cols)
    flat = r_code * len(cols) + c_code
    # pandas skips NaN values: they contribute to neither sums nor counts
    ok = ~np.isnan(vv)
    flat, vv = flat[ok], vv[ok]
    counts = np.bincount(flat, minlength=n_cells).astype(np.float64)
    if aggfunc in ("mean", "sum", "count"):
        sums = np.bincount(flat, weights=vv, minlength=n_cells)
        counts_nan = np.where(counts > 0, counts, np.nan)
        cell = {"sum": sums, "count": counts_nan,
                "mean": np.divide(sums, counts,
                                  out=np.full(n_cells, np.nan),
                                  where=counts > 0)}[aggfunc]
        if aggfunc == "sum":
            cell = np.where(counts > 0, cell, np.nan)
    else:
        cell = np.full(n_cells, np.inf if aggfunc == "min" else -np.inf)
        (np.minimum if aggfunc == "min" else np.maximum).at(cell, flat, vv)
        cell = np.where(counts > 0, cell, np.nan)
    grid = cell.reshape(len(rows), len(cols))

    def _agg_flat(v, codes, n):
        cnt = np.bincount(codes, minlength=n).astype(np.float64)
        if aggfunc == "count":
            return np.where(cnt > 0, cnt, np.nan)
        if aggfunc in ("mean", "sum"):
            s = np.bincount(codes, weights=v, minlength=n)
            if aggfunc == "sum":
                return np.where(cnt > 0, s, np.nan)
            return np.divide(s, cnt, out=np.full(n, np.nan), where=cnt > 0)
        m = np.full(n, np.inf if aggfunc == "min" else -np.inf)
        (np.minimum if aggfunc == "min" else np.maximum).at(m, codes, v)
        return np.where(cnt > 0, m, np.nan)

    out_cols = {str(c): grid[:, j] for j, c in enumerate(cols)}
    out_rows = rows
    if margins:
        row_all = _agg_flat(vv, r_code[ok], len(rows))   # All column
        col_all = _agg_flat(vv, c_code[ok], len(cols))   # All row
        grand = _agg_flat(vv, np.zeros(len(vv), np.int64), 1)[0]
        out_cols = {k: np.concatenate([v, [col_all[j]]])
                    for j, (k, v) in enumerate(out_cols.items())}
        out_cols[margins_name] = np.concatenate([row_all, [grand]])
        out_rows = np.concatenate([rows.astype(object),
                                   np.array([margins_name], object)])
    # the index is attached directly — building it as a data column could
    # collide with a pivot column that stringifies to the same name
    res = CycloneFrame(out_cols)
    res._index = out_rows
    res._index_name = index
    return res

