"""CycloneFrame / CycloneSeries — the pandas-facade implementation.

Columns are numpy arrays of equal length; the implicit index is positional
(the reference's pandas-on-Spark attaches a distributed default index for
the same reason — frame.py's NATURAL_ORDER_COLUMN — which collapses to row
order here).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


def _is_null(arr: np.ndarray) -> np.ndarray:
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    if arr.dtype == object:
        return np.array([v is None or (isinstance(v, float) and np.isnan(v))
                         for v in arr], dtype=bool)
    return np.zeros(len(arr), dtype=bool)


class CycloneSeries:
    """1-D labeled column (ref: pyspark/pandas/series.py)."""

    def __init__(self, values, name: str = ""):
        self.values = np.asarray(values)
        self.name = name

    # -- arithmetic / comparison (elementwise, numpy semantics) ---------------
    def _binop(self, other, op) -> "CycloneSeries":
        rhs = other.values if isinstance(other, CycloneSeries) else other
        return CycloneSeries(op(self.values, rhs), self.name)

    def __add__(self, o):
        return self._binop(o, np.add)

    def __sub__(self, o):
        return self._binop(o, np.subtract)

    def __mul__(self, o):
        return self._binop(o, np.multiply)

    def __truediv__(self, o):
        return self._binop(o, np.divide)

    def __eq__(self, o):  # noqa: PYI032 — pandas-style elementwise eq
        return self._binop(o, np.equal)

    def __ne__(self, o):  # noqa: PYI032
        return self._binop(o, np.not_equal)

    def __lt__(self, o):
        return self._binop(o, np.less)

    def __le__(self, o):
        return self._binop(o, np.less_equal)

    def __gt__(self, o):
        return self._binop(o, np.greater)

    def __ge__(self, o):
        return self._binop(o, np.greater_equal)

    def __and__(self, o):
        return self._binop(o, np.logical_and)

    def __or__(self, o):
        return self._binop(o, np.logical_or)

    def __invert__(self):
        return CycloneSeries(np.logical_not(self.values), self.name)

    def __len__(self) -> int:
        return len(self.values)

    def __iter__(self):
        return iter(self.values)

    def __getitem__(self, i):
        return self.values[i]

    # -- reductions ------------------------------------------------------------
    def sum(self):
        return self.values.sum()

    def mean(self):
        return float(np.mean(self.values))

    def std(self):
        n = len(self.values)
        return float(np.std(self.values, ddof=1)) if n > 1 else float("nan")

    def min(self):
        return self.values.min()

    def max(self):
        return self.values.max()

    def count(self) -> int:
        return int((~_is_null(self.values)).sum())

    def nunique(self) -> int:
        return len(np.unique(self.values[~_is_null(self.values)]))

    # -- transforms ------------------------------------------------------------
    def map(self, f: Callable) -> "CycloneSeries":
        return CycloneSeries(np.array([f(v) for v in self.values]), self.name)

    apply = map

    def astype(self, dtype) -> "CycloneSeries":
        return CycloneSeries(self.values.astype(dtype), self.name)

    def isna(self) -> "CycloneSeries":
        return CycloneSeries(_is_null(self.values), self.name)

    def fillna(self, value) -> "CycloneSeries":
        out = self.values.copy()
        out[_is_null(out)] = value
        return CycloneSeries(out, self.name)

    def unique(self) -> np.ndarray:
        seen, out = set(), []
        for v in self.values:
            if v not in seen:
                seen.add(v)
                out.append(v)
        return np.array(out, dtype=self.values.dtype)

    def value_counts(self) -> "CycloneSeries":
        vals, counts = np.unique(self.values, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        s = CycloneSeries(counts[order], self.name)
        s.index = vals[order]
        return s

    def to_numpy(self) -> np.ndarray:
        return self.values

    def to_list(self) -> list:
        return self.values.tolist()

    def __repr__(self):
        return f"CycloneSeries({self.name!r}, {self.values!r})"


class _GroupBy:
    """(ref: pyspark/pandas/groupby.py) — delegates to the SQL aggregate."""

    def __init__(self, frame: "CycloneFrame", keys: List[str]):
        self._frame = frame
        self._keys = keys

    def _agg(self, fns: Dict[str, str], suffix: bool) -> "CycloneFrame":
        from cycloneml_tpu.sql import functions as F
        from cycloneml_tpu.sql.session import CycloneSession
        df = CycloneSession().create_data_frame(
            {k: v for k, v in self._frame._cols.items()})
        agg_cols = []
        for col, fn in fns.items():
            fobj = {"sum": F.sum, "mean": F.avg, "avg": F.avg, "min": F.min,
                    "max": F.max, "count": F.count}[fn]
            agg_cols.append(fobj(col).alias(f"{col}_{fn}" if suffix else col))
        out = df.group_by(*self._keys).agg(*agg_cols).to_dict()
        return CycloneFrame(out)

    def agg(self, spec: Dict[str, str]) -> "CycloneFrame":
        return self._agg(spec, suffix=True)

    def _all_numeric(self, fn: str) -> "CycloneFrame":
        cols = {c: fn for c in self._frame.columns
                if c not in self._keys
                and self._frame._cols[c].dtype != object}
        # plain pandas naming: df.groupby(k).sum() keeps column names
        return self._agg(cols, suffix=False)

    def sum(self):
        return self._all_numeric("sum")

    def mean(self):
        return self._all_numeric("mean")

    def min(self):
        return self._all_numeric("min")

    def max(self):
        return self._all_numeric("max")

    def count(self):
        rest = [c for c in self._frame.columns if c not in self._keys]
        return self._agg({c: "count" for c in rest}, suffix=False)


class CycloneFrame:
    """2-D table (ref: pyspark/pandas/frame.py)."""

    def __init__(self, data: Union[Dict[str, Any], "CycloneFrame"]):
        if isinstance(data, CycloneFrame):
            self._cols = {k: v.copy() for k, v in data._cols.items()}
            return
        cols = {}
        n = None
        for k, v in data.items():
            arr = v.values if isinstance(v, CycloneSeries) else np.asarray(v)
            if arr.dtype.kind in "US":
                arr = arr.astype(object)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(f"column {k!r}: length {len(arr)} != {n}")
            cols[k] = arr
        self._cols = cols

    # -- metadata --------------------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    @property
    def shape(self):
        n = len(next(iter(self._cols.values()))) if self._cols else 0
        return (n, len(self._cols))

    @property
    def dtypes(self) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def __len__(self) -> int:
        return self.shape[0]

    # -- selection -------------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, str):
            return CycloneSeries(self._cols[key], key)
        if isinstance(key, list):
            return CycloneFrame({k: self._cols[k] for k in key})
        if isinstance(key, CycloneSeries):  # boolean mask
            mask = np.asarray(key.values, dtype=bool)
            return CycloneFrame({k: v[mask] for k, v in self._cols.items()})
        raise TypeError(f"cannot index with {type(key).__name__}")

    def __setitem__(self, key: str, value) -> None:
        arr = value.values if isinstance(value, CycloneSeries) else value
        arr = np.asarray(arr)
        if arr.ndim == 0:
            arr = np.full(len(self), arr[()])
        if self._cols and len(arr) != len(self):
            raise ValueError(
                f"column {key!r}: length {len(arr)} != {len(self)}")
        self._cols[key] = arr

    def assign(self, **kw) -> "CycloneFrame":
        out = CycloneFrame(self)
        for k, v in kw.items():
            out[k] = v(out) if callable(v) else v
        return out

    def drop(self, columns: Sequence[str]) -> "CycloneFrame":
        drop = set([columns] if isinstance(columns, str) else columns)
        return CycloneFrame({k: v for k, v in self._cols.items()
                             if k not in drop})

    def rename(self, columns: Dict[str, str]) -> "CycloneFrame":
        return CycloneFrame({columns.get(k, k): v
                             for k, v in self._cols.items()})

    # -- rows ------------------------------------------------------------------
    def head(self, n: int = 5) -> "CycloneFrame":
        return CycloneFrame({k: v[:n] for k, v in self._cols.items()})

    def tail(self, n: int = 5) -> "CycloneFrame":
        return CycloneFrame({k: v[-n:] if n else v[:0]
                             for k, v in self._cols.items()})

    def sort_values(self, by, ascending: bool = True) -> "CycloneFrame":
        keys = [by] if isinstance(by, str) else list(by)
        order = np.lexsort([self._cols[k] for k in reversed(keys)])
        if not ascending:
            order = order[::-1]
        return CycloneFrame({k: v[order] for k, v in self._cols.items()})

    # -- missing data ----------------------------------------------------------
    def isna(self) -> "CycloneFrame":
        return CycloneFrame({k: _is_null(v) for k, v in self._cols.items()})

    def fillna(self, value) -> "CycloneFrame":
        return CycloneFrame({k: CycloneSeries(v).fillna(value).values
                             for k, v in self._cols.items()})

    def dropna(self) -> "CycloneFrame":
        if not self._cols:
            return CycloneFrame({})
        keep = ~np.logical_or.reduce([_is_null(v)
                                      for v in self._cols.values()])
        return CycloneFrame({k: v[keep] for k, v in self._cols.items()})

    # -- combine ---------------------------------------------------------------
    def merge(self, other: "CycloneFrame", on, how: str = "inner"
              ) -> "CycloneFrame":
        from cycloneml_tpu.sql.session import CycloneSession
        s = CycloneSession()
        left = s.create_data_frame(dict(self._cols))
        right = s.create_data_frame(dict(other._cols))
        return CycloneFrame(left.join(right, on=on, how=how).to_dict())

    def groupby(self, by) -> _GroupBy:
        return _GroupBy(self, [by] if isinstance(by, str) else list(by))

    # -- stats -----------------------------------------------------------------
    def describe(self) -> "CycloneFrame":
        stats = ["count", "mean", "std", "min", "max"]
        out: Dict[str, list] = {"summary": stats}
        for k, v in self._cols.items():
            if v.dtype == object:
                continue
            s = CycloneSeries(v)
            out[k] = [s.count(), s.mean(), s.std(), s.min(), s.max()]
        return CycloneFrame({k: np.asarray(v, dtype=object)
                             if k == "summary" else np.asarray(v, dtype=float)
                             for k, v in out.items()})

    def apply(self, f: Callable, axis: int = 0):
        if axis == 0:
            return CycloneFrame({k: np.asarray(f(CycloneSeries(v, k)))
                                 for k, v in self._cols.items()})
        rows = self.to_records()
        return CycloneSeries(np.array([f(r) for r in rows]))

    # -- bridges ---------------------------------------------------------------
    def to_records(self) -> List[Dict[str, Any]]:
        cols = self.columns
        return [{c: self._cols[c][i] for c in cols}
                for i in range(len(self))]

    def to_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._cols)

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({k: v for k, v in self._cols.items()})

    @classmethod
    def from_pandas(cls, pdf) -> "CycloneFrame":
        return cls({c: pdf[c].to_numpy() for c in pdf.columns})

    def to_sql_df(self, session=None):
        from cycloneml_tpu.sql.session import CycloneSession
        return (session or CycloneSession()).create_data_frame(
            dict(self._cols))

    def __repr__(self):
        n, m = self.shape
        return f"CycloneFrame({n} rows x {m} cols: {self.columns})"


def read_csv(path: str, header: bool = True,
             delimiter: str = ",") -> CycloneFrame:
    from cycloneml_tpu.sql.session import CycloneSession
    return CycloneFrame(
        CycloneSession().read_csv(path, header, delimiter).to_dict())
