"""CycloneFrame / CycloneSeries — the pandas-facade implementation.

Columns are numpy arrays of equal length; the implicit index is positional
(the reference's pandas-on-Spark attaches a distributed default index for
the same reason — frame.py's NATURAL_ORDER_COLUMN — which collapses to row
order here).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np


def _narrow_object(out: np.ndarray) -> np.ndarray:
    """Cast an object array to a numeric dtype when every non-null element
    is numeric (None → NaN); ints stay int64 when no nulls, anything mixed
    or stringy keeps object — the dtype-restoring step for values that
    round-tripped through tuples/lists."""
    vals = [x for x in out if x is not None]
    if not vals:
        return out
    if all(isinstance(x, (bool, np.bool_)) for x in vals):
        return out.astype(bool) if len(vals) == len(out) else out
    if all(isinstance(x, (int, np.integer)) for x in vals):
        if len(vals) == len(out):
            return out.astype(np.int64)
        return np.array([np.nan if x is None else float(x) for x in out])
    if all(isinstance(x, (int, float, np.integer, np.floating))
           for x in vals):
        return np.array([np.nan if x is None else float(x) for x in out])
    return out


def _is_null(arr: np.ndarray) -> np.ndarray:
    if arr.dtype.kind == "f":
        return np.isnan(arr)
    if arr.dtype == object:
        return np.array([v is None or (isinstance(v, float) and np.isnan(v))
                         for v in arr], dtype=bool)
    return np.zeros(len(arr), dtype=bool)


_NAN_KEY = object()  # all NaN/None keys compare equal, as pandas does


def _norm_key(v):
    if v is None or (isinstance(v, (float, np.floating)) and np.isnan(v)):
        return _NAN_KEY
    return v


def _label_array(labels) -> np.ndarray:
    """1-D object array of labels — tuples STAY single labels (np.array
    would explode a list of tuples into a 2-D array)."""
    out = np.empty(len(labels), dtype=object)
    for i, l in enumerate(labels):
        out[i] = l
    return out


def _duplicated_mask(cols: Sequence[np.ndarray], keep) -> np.ndarray:
    """True where the row's key tuple has been seen before (keep='first'),
    will be seen again (keep='last'), or appears more than once
    (keep=False) — the pandas duplicated() contract (NaN keys equal)."""
    n = len(cols[0])
    keys = list(zip(*[[_norm_key(v) for v in np.asarray(c, dtype=object)]
                      for c in cols]))
    out = np.zeros(n, dtype=bool)
    if keep == "first":
        seen = set()
        for i, k in enumerate(keys):
            out[i] = k in seen
            seen.add(k)
    elif keep == "last":
        seen = set()
        for i in range(n - 1, -1, -1):
            out[i] = keys[i] in seen
            seen.add(keys[i])
    elif keep is False:
        from collections import Counter
        counts = Counter(keys)
        for i, k in enumerate(keys):
            out[i] = counts[k] > 1
    else:
        raise ValueError(f"keep must be 'first', 'last' or False, "
                         f"got {keep!r}")
    return out


class CycloneSeries:
    """1-D labeled column (ref: pyspark/pandas/series.py). ``index`` is an
    optional label array; None means positional (RangeIndex)."""

    def __init__(self, values, name: str = "", index=None):
        self.values = np.asarray(values)
        self.name = name
        self.index = None if index is None else np.asarray(index)

    # -- arithmetic / comparison (elementwise, numpy semantics) ---------------
    def _binop(self, other, op) -> "CycloneSeries":
        if isinstance(other, CycloneSeries):
            if (self.index is not None and other.index is not None
                    and not np.array_equal(self.index, other.index)):
                # label alignment on the index union, NaN where one side is
                # missing — the pandas contract (frame.py align paths)
                union = np.unique(np.concatenate([self.index, other.index]))

                def reindexed(s):
                    pos = {k: i for i, k in enumerate(s.index)}
                    out = np.full(len(union), np.nan)
                    for j, k in enumerate(union):
                        if k in pos:
                            out[j] = s.values[pos[k]]
                    return out

                return CycloneSeries(op(reindexed(self), reindexed(other)),
                                     self.name, index=union)
            rhs = other.values
        else:
            rhs = other
        return CycloneSeries(op(self.values, rhs), self.name,
                             index=self.index)

    def __add__(self, o):
        return self._binop(o, np.add)

    def __sub__(self, o):
        return self._binop(o, np.subtract)

    def __mul__(self, o):
        return self._binop(o, np.multiply)

    def __truediv__(self, o):
        return self._binop(o, np.divide)

    def __eq__(self, o):  # noqa: PYI032 — pandas-style elementwise eq
        return self._binop(o, np.equal)

    def __ne__(self, o):  # noqa: PYI032
        return self._binop(o, np.not_equal)

    def __lt__(self, o):
        return self._binop(o, np.less)

    def __le__(self, o):
        return self._binop(o, np.less_equal)

    def __gt__(self, o):
        return self._binop(o, np.greater)

    def __ge__(self, o):
        return self._binop(o, np.greater_equal)

    def __and__(self, o):
        return self._binop(o, np.logical_and)

    def __or__(self, o):
        return self._binop(o, np.logical_or)

    def __invert__(self):
        return CycloneSeries(np.logical_not(self.values), self.name)

    def __len__(self) -> int:
        return len(self.values)

    def __iter__(self):
        return iter(self.values)

    def __getitem__(self, i):
        if isinstance(i, str) and self.index is not None:
            # label lookup, as pandas: s['col'] on an iterrows row
            pos = np.nonzero(self.index == i)[0]
            if len(pos) == 0:
                raise KeyError(i)
            return self.values[pos[0]] if len(pos) == 1 \
                else CycloneSeries(self.values[pos], self.name,
                                  index=self.index[pos])
        return self.values[i]

    # -- reductions (skipna=True — the pandas default) -------------------------
    def _notnull(self) -> np.ndarray:
        return self.values[~_is_null(self.values)]

    def sum(self):
        v = self.values
        return v[~_is_null(v)].sum() if v.dtype.kind in "fO" else v.sum()

    def mean(self):
        return float(np.mean(self._notnull()))

    def std(self):
        v = self._notnull()
        return float(np.std(v, ddof=1)) if len(v) > 1 else float("nan")

    def min(self):
        v = self._notnull()
        return v.min() if len(v) else np.nan

    def max(self):
        v = self._notnull()
        return v.max() if len(v) else np.nan

    def count(self) -> int:
        return int((~_is_null(self.values)).sum())

    def nunique(self) -> int:
        return len(np.unique(self.values[~_is_null(self.values)]))

    # -- transforms ------------------------------------------------------------
    def map(self, f: Callable) -> "CycloneSeries":
        return CycloneSeries(np.array([f(v) for v in self.values]), self.name)

    apply = map

    def astype(self, dtype) -> "CycloneSeries":
        return CycloneSeries(self.values.astype(dtype), self.name)

    def isna(self) -> "CycloneSeries":
        return CycloneSeries(_is_null(self.values), self.name)

    def fillna(self, value) -> "CycloneSeries":
        out = self.values.copy()
        out[_is_null(out)] = value
        return CycloneSeries(out, self.name)

    def unique(self) -> np.ndarray:
        seen, out = set(), []
        for v in self.values:
            if v not in seen:
                seen.add(v)
                out.append(v)
        return np.array(out, dtype=self.values.dtype)

    def value_counts(self) -> "CycloneSeries":
        vals, counts = np.unique(self.values, return_counts=True)
        order = np.argsort(-counts, kind="stable")
        s = CycloneSeries(counts[order], self.name)
        s.index = vals[order]
        return s

    def notna(self) -> "CycloneSeries":
        return CycloneSeries(~_is_null(self.values), self.name,
                             index=self.index)

    def abs(self) -> "CycloneSeries":
        return CycloneSeries(np.abs(self.values), self.name,
                             index=self.index)

    def round(self, decimals: int = 0) -> "CycloneSeries":
        return CycloneSeries(np.round(self.values, decimals), self.name,
                             index=self.index)

    def clip(self, lower=None, upper=None) -> "CycloneSeries":
        v = self.values
        if lower is not None:
            v = np.maximum(v, lower)
        if upper is not None:
            v = np.minimum(v, upper)
        return CycloneSeries(v, self.name, index=self.index)

    def diff(self, periods: int = 1) -> "CycloneSeries":
        shifted = self.shift(periods)
        return CycloneSeries(
            self.values.astype(np.float64) - shifted.values,
            self.name, index=self.index)

    def shift(self, periods: int = 1, fill_value=None) -> "CycloneSeries":
        """(ref pandas shift) — numeric columns widen to float64 so the
        hole can hold NaN; a non-null ``fill_value`` keeps the dtype
        (promoted only as the fill itself demands), as pandas does."""
        v = self.values
        if v.dtype == object:
            out = np.full(len(v), fill_value, dtype=object)
        elif fill_value is None:
            out = np.full(len(v), np.nan, dtype=np.float64)
            v = v.astype(np.float64)
        else:
            dt = np.result_type(v.dtype, np.min_scalar_type(fill_value))
            out = np.full(len(v), fill_value, dtype=dt)
            v = v.astype(dt)
        if periods >= 0:
            out[periods:] = v[:len(v) - periods] if periods else v
        else:
            out[:periods] = v[-periods:]
        return CycloneSeries(out, self.name, index=self.index)

    def pct_change(self, periods: int = 1) -> "CycloneSeries":
        prev = self.shift(periods).values
        return CycloneSeries(self.values.astype(np.float64) / prev - 1.0,
                             self.name, index=self.index)

    def _nan_cum(self, op, identity) -> "CycloneSeries":
        """Cumulative op that SKIPS NaNs (they stay NaN in place but do not
        poison the running value) — the pandas contract."""
        v = self.values.astype(np.float64)
        null = np.isnan(v)
        filled = np.where(null, identity, v)
        out = op(filled)
        out = np.where(null, np.nan, out)
        return CycloneSeries(out, self.name, index=self.index)

    def cumsum(self):
        return self._nan_cum(np.cumsum, 0.0)

    def cumprod(self):
        return self._nan_cum(np.cumprod, 1.0)

    def cummax(self):
        return self._nan_cum(np.maximum.accumulate, -np.inf)

    def cummin(self):
        return self._nan_cum(np.minimum.accumulate, np.inf)

    def rank(self, method: str = "average",
             ascending: bool = True) -> "CycloneSeries":
        """(ref pandas Series.rank, na_option='keep') — average/min/max/
        dense via scipy rankdata; 'first' by stable sort position."""
        v = self.values.astype(np.float64)
        null = np.isnan(v)
        body = v[~null]
        if not ascending:
            body = -body
        if method == "first":
            order = np.argsort(body, kind="stable")
            r = np.empty(len(body), dtype=np.float64)
            r[order] = np.arange(1, len(body) + 1, dtype=np.float64)
        else:
            from scipy.stats import rankdata
            r = rankdata(body, method=method).astype(np.float64)
        out = np.full(len(v), np.nan)
        out[~null] = r
        return CycloneSeries(out, self.name, index=self.index)

    def quantile(self, q=0.5, interpolation: str = "linear"):
        v = self.values.astype(np.float64)
        v = v[~np.isnan(v)]
        if np.isscalar(q):
            return float(np.quantile(v, q, method=interpolation)) \
                if len(v) else float("nan")
        vals = (np.quantile(v, list(q), method=interpolation)
                if len(v) else np.full(len(list(q)), np.nan))
        return CycloneSeries(vals, self.name, index=np.asarray(q))

    def median(self):
        v = self.values.astype(np.float64)
        return float(np.median(v[~np.isnan(v)]))

    def var(self):
        v = self._notnull()
        return float(np.var(v, ddof=1)) if len(v) > 1 else float("nan")

    def prod(self):
        v = self.values
        if v.dtype.kind == "f":
            return v[~np.isnan(v)].prod()
        return v.prod()

    def mode(self) -> "CycloneSeries":
        v = self.values
        v = v[~_is_null(v)]
        vals, counts = np.unique(v, return_counts=True)
        return CycloneSeries(np.sort(vals[counts == counts.max()]),
                             self.name)

    def idxmax(self):
        v = self.values.astype(np.float64)
        return self._label(int(np.nanargmax(v)))

    def idxmin(self):
        v = self.values.astype(np.float64)
        return self._label(int(np.nanargmin(v)))

    def _label(self, pos: int):
        return pos if self.index is None else self.index[pos]

    def any(self) -> bool:
        # skipna=True (the pandas default): NaN is not truthy here
        return bool(np.asarray(self._notnull(), dtype=bool).any())

    def all(self) -> bool:
        return bool(np.asarray(self._notnull(), dtype=bool).all())

    def isin(self, values) -> "CycloneSeries":
        vset = {_norm_key(v) for v in values}
        return CycloneSeries(
            np.array([_norm_key(v) in vset for v in self.values],
                     dtype=bool),
            self.name, index=self.index)

    def between(self, left, right,
                inclusive: str = "both") -> "CycloneSeries":
        v = self.values
        lo = v >= left if inclusive in ("both", "left") else v > left
        hi = v <= right if inclusive in ("both", "right") else v < right
        return CycloneSeries(lo & hi, self.name, index=self.index)

    def where(self, cond, other=np.nan) -> "CycloneSeries":
        c = np.asarray(cond.values if isinstance(cond, CycloneSeries)
                       else cond, dtype=bool)
        o = other.values if isinstance(other, CycloneSeries) else other
        v = self.values
        if v.dtype.kind in "iub" and not isinstance(o, np.ndarray) \
                and (o is np.nan or (isinstance(o, float) and np.isnan(o))):
            v = v.astype(np.float64)  # hole must hold NaN
        return CycloneSeries(np.where(c, v, o), self.name, index=self.index)

    def mask(self, cond, other=np.nan) -> "CycloneSeries":
        c = np.asarray(cond.values if isinstance(cond, CycloneSeries)
                       else cond, dtype=bool)
        return self.where(~c, other)

    def _nl(self, n: int, largest: bool) -> "CycloneSeries":
        v = self.values.astype(np.float64)
        pos = np.nonzero(~np.isnan(v))[0]
        key = -v[pos] if largest else v[pos]
        order = pos[np.argsort(key, kind="stable")][:n]
        idx = (self.index[order] if self.index is not None else order)
        return CycloneSeries(self.values[order], self.name, index=idx)

    def nlargest(self, n: int = 5) -> "CycloneSeries":
        return self._nl(n, True)

    def nsmallest(self, n: int = 5) -> "CycloneSeries":
        return self._nl(n, False)

    def duplicated(self, keep: str = "first") -> "CycloneSeries":
        return CycloneSeries(_duplicated_mask([self.values], keep),
                             self.name, index=self.index)

    def drop_duplicates(self, keep: str = "first") -> "CycloneSeries":
        m = ~_duplicated_mask([self.values], keep)
        pos = np.nonzero(m)[0]
        return CycloneSeries(
            self.values[pos], self.name,
            index=self.index[pos] if self.index is not None else pos)

    def sort_values(self, ascending: bool = True) -> "CycloneSeries":
        order = np.argsort(self.values, kind="stable")
        if not ascending:
            order = order[::-1]
        idx = self.index[order] if self.index is not None else order
        return CycloneSeries(self.values[order], self.name, index=idx)

    def _pairwise_complete(self, other: "CycloneSeries"):
        a = self.values.astype(np.float64)
        b = np.asarray(other.values, dtype=np.float64)
        ok = ~(np.isnan(a) | np.isnan(b))
        return a[ok], b[ok]

    def corr(self, other: "CycloneSeries") -> float:
        a, b = self._pairwise_complete(other)
        return float(np.corrcoef(a, b)[0, 1])

    def cov(self, other: "CycloneSeries") -> float:
        a, b = self._pairwise_complete(other)
        return float(np.cov(a, b, ddof=1)[0, 1])

    def rolling(self, window: int, min_periods: Optional[int] = None
                ) -> "_Rolling":
        return _Rolling(self.values, window,
                        window if min_periods is None else min_periods,
                        self.name, self.index)

    def expanding(self, min_periods: int = 1) -> "_Rolling":
        return _Rolling(self.values, None, min_periods, self.name,
                        self.index)

    @property
    def str(self) -> "_StrAccessor":
        return _StrAccessor(self)

    @property
    def dt(self) -> "_DtAccessor":
        return _DtAccessor(self)

    def to_numpy(self) -> np.ndarray:
        return self.values

    def to_list(self) -> list:
        return self.values.tolist()

    def unstack(self) -> "CycloneFrame":
        """Series with a tuple (MultiIndex) index → frame: the LAST index
        level becomes the columns (ref pandas Series.unstack; NaN where a
        (row, column) pair is absent, ValueError on duplicate pairs)."""
        idx = self.index
        if not (len(idx) and isinstance(idx[0], tuple)):
            raise ValueError("unstack needs a MultiIndex (tuple labels)")
        if len(set(idx)) != len(idx):
            raise ValueError(
                "Index contains duplicate entries, cannot reshape")
        rows = sorted({t[:-1] for t in idx})
        cols = sorted({t[-1] for t in idx})
        data = {c: np.full(len(rows), np.nan) for c in cols}
        rpos = {r: i for i, r in enumerate(rows)}
        for t, v in zip(idx, self.values):
            data[t[-1]][rpos[t[:-1]]] = v
        out = CycloneFrame(data)
        row_labels = [r[0] if len(r) == 1 else r for r in rows]
        out._index = np.array(row_labels, dtype=object)
        names = getattr(self, "index_name", None)
        if isinstance(names, list) and len(names) == len(idx[0]):
            rest = names[:-1]
            out._index_name = rest[0] if len(rest) == 1 else rest
        else:
            out._index_name = "index"
        return out

    def __repr__(self):
        return f"CycloneSeries({self.name!r}, {self.values!r})"


class _Rolling:
    """Rolling (fixed window) / expanding (window=None) aggregations over a
    1-D numeric array — NaN where fewer than ``min_periods`` observations
    exist, matching pandas (ref: pyspark/pandas/window.py Rolling)."""

    def __init__(self, values: np.ndarray, window: Optional[int],
                 min_periods: int, name: str, index):
        self._v = np.asarray(values, dtype=np.float64)
        self._window = window
        self._min = min_periods
        self._name = name
        self._index = index

    def _apply(self, fn) -> CycloneSeries:
        v, n = self._v, len(self._v)
        out = np.full(n, np.nan)
        for i in range(n):
            lo = 0 if self._window is None else max(0, i + 1 - self._window)
            win = v[lo:i + 1]
            win = win[~np.isnan(win)]
            if len(win) >= self._min and len(win):
                out[i] = fn(win)
        return CycloneSeries(out, self._name, index=self._index)

    def sum(self):
        return self._apply(np.sum)

    def mean(self):
        return self._apply(np.mean)

    def min(self):
        return self._apply(np.min)

    def max(self):
        return self._apply(np.max)

    def std(self):
        return self._apply(lambda w: np.std(w, ddof=1)
                           if len(w) > 1 else np.nan)

    def count(self):
        return self._apply(len)


class _FrameRolling:
    """Column-wise rolling over a frame's numeric columns."""

    def __init__(self, frame: "CycloneFrame", window, min_periods):
        self._frame = frame
        self._window = window
        self._min = min_periods

    def _apply(self, op: str) -> "CycloneFrame":
        out = {}
        for k, v in self._frame._cols.items():
            if v.dtype.kind in "if":
                r = _Rolling(v, self._window,
                             self._min if self._min is not None
                             else (self._window or 1), k, None)
                out[k] = getattr(r, op)().values
        return self._frame._like(out)

    def sum(self):
        return self._apply("sum")

    def mean(self):
        return self._apply("mean")

    def min(self):
        return self._apply("min")

    def max(self):
        return self._apply("max")

    def std(self):
        return self._apply("std")


class _StrAccessor:
    """Vectorized string methods (ref: pyspark/pandas/strings.py)."""

    def __init__(self, s: CycloneSeries):
        self._s = s

    def _map(self, f, dtype=object) -> CycloneSeries:
        vals = [None if v is None else f(v) for v in self._s.values]
        if dtype is not object and any(v is None for v in vals):
            # pandas propagates nulls as NaN rather than failing the cast:
            # len() -> float64 with NaN, boolean tests -> object with NaN
            vals = [np.nan if v is None else v for v in vals]
            dtype = np.float64 if dtype is np.int64 else object
        return CycloneSeries(np.array(vals, dtype=dtype), self._s.name,
                             index=self._s.index)

    def lower(self):
        return self._map(str.lower)

    def upper(self):
        return self._map(str.upper)

    def strip(self):
        return self._map(str.strip)

    def len(self):
        return self._map(len, dtype=np.int64)

    def contains(self, pat: str, regex: bool = True):
        import re
        if regex:
            rx = re.compile(pat)
            return self._map(lambda v: rx.search(v) is not None, dtype=bool)
        return self._map(lambda v: pat in v, dtype=bool)

    def startswith(self, pat: str):
        return self._map(lambda v: v.startswith(pat), dtype=bool)

    def endswith(self, pat: str):
        return self._map(lambda v: v.endswith(pat), dtype=bool)

    def replace(self, pat: str, repl: str, regex: bool = True):
        import re
        if regex:
            rx = re.compile(pat)
            return self._map(lambda v: rx.sub(repl, v))
        return self._map(lambda v: v.replace(pat, repl))

    def slice(self, start=None, stop=None, step=None):
        return self._map(lambda v: v[start:stop:step])

    def split(self, pat: str = " "):
        return self._map(lambda v: v.split(pat))

    def cat(self, sep: str = "") -> str:
        return sep.join(v for v in self._s.values if v is not None)


class _DtAccessor:
    """Datetime component accessors over datetime64 columns (ref:
    pyspark/pandas/datetimes.py)."""

    def __init__(self, s: CycloneSeries):
        self._v = np.asarray(s.values, dtype="datetime64[s]")
        self._name = s.name
        self._index = s.index

    def _series(self, vals, dtype=np.int64) -> CycloneSeries:
        return CycloneSeries(np.asarray(vals, dtype=dtype), self._name,
                             index=self._index)

    @property
    def year(self):
        return self._series(self._v.astype("M8[Y]").astype(np.int64) + 1970)

    @property
    def month(self):
        return self._series(
            self._v.astype("M8[M]").astype(np.int64) % 12 + 1)

    @property
    def day(self):
        return self._series((self._v.astype("M8[D]")
                             - self._v.astype("M8[M]").astype("M8[D]"))
                            .astype(np.int64) + 1)

    @property
    def hour(self):
        return self._series((self._v.astype("M8[h]")
                             - self._v.astype("M8[D]").astype("M8[h]"))
                            .astype(np.int64))

    @property
    def minute(self):
        return self._series((self._v.astype("M8[m]")
                             - self._v.astype("M8[h]").astype("M8[m]"))
                            .astype(np.int64))

    @property
    def second(self):
        return self._series((self._v.astype("M8[s]")
                             - self._v.astype("M8[m]").astype("M8[s]"))
                            .astype(np.int64))

    @property
    def dayofweek(self):
        # 1970-01-01 is a Thursday = 3 under pandas' Monday=0 convention
        return self._series(
            (self._v.astype("M8[D]").astype(np.int64) + 3) % 7)

    @property
    def date(self):
        return CycloneSeries(self._v.astype("M8[D]"), self._name,
                             index=self._index)


class _LocIndexer:
    """Label-based row access (ref: pyspark/pandas/indexing.py loc)."""

    def __init__(self, frame: "CycloneFrame"):
        self._f = frame

    def __getitem__(self, key):
        f = self._f
        idx = f.index
        if (isinstance(f._index_name, list) and isinstance(key, tuple)
                and len(key) == len(f._index_name)):
            # MultiIndex label lookup: a full tuple addresses one label
            # (takes precedence over the (rows, cols) reading, as pandas';
            # no match falls THROUGH so loc[(label_tuple), col] still works)
            pos = np.array([i for i, t in enumerate(idx) if t == key],
                           dtype=np.int64)
            if len(pos) == 1:
                return {c: f._cols[c][pos[0]] for c in f.columns}
            if len(pos):
                return f._take(pos)
        if isinstance(key, tuple) and len(key) == 2:
            rows, cols = key
            sub = self[rows]
            if isinstance(sub, dict):  # unique row label -> row mapping
                if isinstance(cols, str):
                    return sub[cols]
                return {c: sub[c] for c in cols}
            if isinstance(cols, str):
                return sub[cols]
            return sub[list(cols)]
        if isinstance(key, CycloneSeries):  # boolean mask
            return f[key]
        if isinstance(key, slice):
            # label slices are INCLUSIVE on both ends in pandas; on a
            # monotonic index a missing bound slices to its insertion
            # point, otherwise it is KeyError; duplicate bound labels on a
            # non-monotonic index are rejected (pandas contract)
            try:
                inc = bool(np.all(idx[:-1] <= idx[1:]))
                dec = not inc and bool(np.all(idx[:-1] >= idx[1:]))
            except TypeError:  # unorderable mixed-type labels
                inc = dec = False
            rev = idx[::-1] if dec else None

            def _bound(label, side):
                hits = np.nonzero(idx == label)[0]
                if len(hits) > 1 and not (inc or dec):
                    raise KeyError(
                        f"Cannot get {side} slice bound for non-unique "
                        f"label: {label!r}")
                if len(hits):
                    return int(hits[0] if side == "left" else hits[-1])
                if inc:
                    p = int(np.searchsorted(
                        idx, label, side="left" if side == "left" else "right"))
                    return p if side == "left" else p - 1
                if dec:
                    p = int(np.searchsorted(
                        rev, label, side="right" if side == "left" else "left"))
                    return (len(f) - p) if side == "left" else len(f) - p - 1
                raise KeyError(label)
            lo = 0 if key.start is None else _bound(key.start, "left")
            hi = (len(f) - 1 if key.stop is None
                  else _bound(key.stop, "right"))
            return f._take(np.arange(lo, hi + 1))
        if isinstance(key, (list, np.ndarray)):
            # every row matching each label, label order outer (pandas
            # duplicate-label semantics). Tuple labels (MultiIndex) compare
            # elementwise — numpy would broadcast a tuple against the index
            pos = []
            for k in key:
                if isinstance(k, tuple):
                    hits = np.array([i for i, t in enumerate(idx) if t == k],
                                    dtype=np.int64)
                else:
                    hits = np.nonzero(idx == k)[0]
                if not len(hits):
                    raise KeyError(k)
                pos.extend(hits)
            return f._take(np.array(pos, dtype=np.int64))
        pos = np.nonzero(idx == key)[0]
        if not len(pos):
            raise KeyError(key)
        if len(pos) == 1:
            return {c: f._cols[c][pos[0]] for c in f.columns}
        return f._take(pos)


class _ILocIndexer:
    """Position-based row access."""

    def __init__(self, frame: "CycloneFrame"):
        self._f = frame

    def __getitem__(self, key):
        f = self._f
        if isinstance(key, int):
            n = len(f)
            if key < 0:
                key += n
            if not 0 <= key < n:
                raise IndexError(key)
            return {c: f._cols[c][key] for c in f.columns}
        if isinstance(key, slice):
            return f._take(np.arange(len(f))[key])
        return f._take(np.asarray(key))


class _GroupBy:
    """(ref: pyspark/pandas/groupby.py) — delegates to the SQL aggregate."""

    def __init__(self, frame: "CycloneFrame", keys: List[str]):
        self._frame = frame
        self._keys = keys

    def _agg(self, fns: Dict[str, str], suffix: bool) -> "CycloneFrame":
        from cycloneml_tpu.sql import functions as F
        from cycloneml_tpu.sql.session import CycloneSession
        df = CycloneSession().create_data_frame(
            {k: v for k, v in self._frame._cols.items()})
        agg_cols = []
        for col, fn in fns.items():
            fobj = {"sum": F.sum, "mean": F.avg, "avg": F.avg, "min": F.min,
                    "max": F.max, "count": F.count}[fn]
            agg_cols.append(fobj(col).alias(f"{col}_{fn}" if suffix else col))
        out = df.group_by(*self._keys).agg(*agg_cols).to_dict()
        return CycloneFrame(out)

    def agg(self, spec: Dict[str, str]) -> "CycloneFrame":
        return self._agg(spec, suffix=True)

    def _all_numeric(self, fn: str) -> "CycloneFrame":
        cols = {c: fn for c in self._frame.columns
                if c not in self._keys
                and self._frame._cols[c].dtype != object}
        # plain pandas naming: df.groupby(k).sum() keeps column names
        return self._agg(cols, suffix=False)

    def sum(self):
        return self._all_numeric("sum")

    def mean(self):
        return self._all_numeric("mean")

    def min(self):
        return self._all_numeric("min")

    def max(self):
        return self._all_numeric("max")

    def count(self):
        rest = [c for c in self._frame.columns if c not in self._keys]
        return self._agg({c: "count" for c in rest}, suffix=False)

    def _groups(self) -> Dict[tuple, list]:
        """key tuple → row positions, first-appearance order preserved."""
        f = self._frame
        key_tuples = list(zip(*[f._cols[k] for k in self._keys]))
        order: Dict[tuple, list] = {}
        for i, t in enumerate(key_tuples):
            order.setdefault(t, []).append(i)
        return order

    def apply(self, func) -> Union["CycloneSeries", "CycloneFrame"]:
        """(ref pandas groupby.apply / pyspark.pandas groupby.py apply):
        call ``func`` on each group's sub-frame, groups in sorted key
        order. Scalar results → a Series indexed by group key; Series
        results → a frame (one row per group, index = group key)."""
        f = self._frame
        order = self._groups()
        results = []
        labels = []
        for t in sorted(order):
            pos = np.asarray(order[t], dtype=np.int64)
            sub = f._take(pos)
            results.append(func(sub))
            labels.append(t[0] if len(self._keys) == 1 else t)
        label_arr = _label_array(labels)
        name = (self._keys[0] if len(self._keys) == 1
                else list(self._keys))
        if all(isinstance(r, CycloneSeries) for r in results):
            cols = list(results[0].index)
            out = CycloneFrame({c: _narrow_object(np.array(
                [r.values[list(r.index).index(c)] for r in results],
                dtype=object)) for c in cols})
            out._index = label_arr
            out._index_name = name
            return out
        out_s = CycloneSeries(_narrow_object(np.array(results, dtype=object)),
                              None, index=label_arr)
        return out_s

    def _per_group_scalar(self, fn: Callable) -> "CycloneFrame":
        """One scalar per (group, non-key numeric column) via the group
        machinery, sorted-key order (pandas sorts groups by default)."""
        f = self._frame
        order = self._groups()
        data_cols = [c for c in f.columns
                     if c not in self._keys and f._cols[c].dtype != object]
        labels, rows = [], {c: [] for c in data_cols}
        for t in sorted(order):
            pos = np.asarray(order[t], dtype=np.int64)
            labels.append(t[0] if len(self._keys) == 1 else t)
            for c in data_cols:
                rows[c].append(fn(f._cols[c][pos]))
        out = CycloneFrame({c: np.asarray(v) for c, v in rows.items()})
        out._index = _label_array(labels)
        out._index_name = (self._keys[0] if len(self._keys) == 1
                           else list(self._keys))
        return out

    def std(self):
        return self._per_group_scalar(
            lambda v: np.std(v.astype(np.float64), ddof=1)
            if len(v) > 1 else np.nan)

    def var(self):
        return self._per_group_scalar(
            lambda v: np.var(v.astype(np.float64), ddof=1)
            if len(v) > 1 else np.nan)

    def median(self):
        return self._per_group_scalar(
            lambda v: np.median(v[~_is_null(v)].astype(np.float64)))

    def nunique(self):
        return self._per_group_scalar(
            lambda v: len(np.unique(v[~_is_null(v)])))

    def _first_last(self, last: bool) -> "CycloneFrame":
        """First/last NON-NULL value per column per group, object columns
        included — the pandas first()/last() contract."""
        f = self._frame
        order = self._groups()
        data_cols = [c for c in f.columns if c not in self._keys]
        labels, rows = [], {c: [] for c in data_cols}
        for t in sorted(order):
            pos = order[t][::-1] if last else order[t]
            labels.append(t[0] if len(self._keys) == 1 else t)
            for c in data_cols:
                vals = f._cols[c]
                rows[c].append(next(
                    (vals[i] for i in pos
                     if _norm_key(vals[i]) is not _NAN_KEY), np.nan))
        out = CycloneFrame({
            c: _narrow_object(np.array(v, dtype=object))
            for c, v in rows.items()})
        out._index = _label_array(labels)
        out._index_name = (self._keys[0] if len(self._keys) == 1
                           else list(self._keys))
        return out

    def first(self):
        return self._first_last(last=False)

    def last(self):
        return self._first_last(last=True)

    def size(self) -> CycloneSeries:
        order = self._groups()
        labels = [t[0] if len(self._keys) == 1 else t
                  for t in sorted(order)]
        return CycloneSeries(
            np.array([len(order[t]) for t in sorted(order)],
                     dtype=np.int64),
            None, index=_label_array(labels))

    # -- row-shaped (length-preserving) group ops -------------------------
    def _scatter(self, per_group: Callable, dtype=np.float64
                 ) -> Dict[str, np.ndarray]:
        """Apply ``per_group(values) -> values`` within each group and
        scatter results back to original row order, per non-key column."""
        f = self._frame
        order = self._groups()
        data_cols = [c for c in f.columns
                     if c not in self._keys and f._cols[c].dtype != object]
        out = {c: np.empty(len(f), dtype=dtype) for c in data_cols}
        for t, pos_list in order.items():
            pos = np.asarray(pos_list, dtype=np.int64)
            for c in data_cols:
                out[c][pos] = per_group(f._cols[c][pos])
        return out

    def transform(self, fn) -> "CycloneFrame":
        """(ref pandas groupby.transform) — broadcast a group aggregate
        back over the group's rows. ``fn`` is an agg name (NaN-skipping,
        like the pandas aggregates) or a callable (applied verbatim)."""
        if callable(fn):
            g = fn
        else:
            g = {"sum": np.nansum, "mean": np.nanmean, "min": np.nanmin,
                 "max": np.nanmax, "median": np.nanmedian,
                 "prod": np.nanprod,
                 "count": lambda v: np.count_nonzero(~np.isnan(v)),
                 "std": lambda v: np.nanstd(v, ddof=1),
                 "var": lambda v: np.nanvar(v, ddof=1)}[fn]
        return self._frame._like(self._scatter(
            lambda v: np.full(len(v), g(v.astype(np.float64)))))

    def cumsum(self) -> "CycloneFrame":
        return self._frame._like(self._scatter(
            lambda v: np.cumsum(v.astype(np.float64))))

    def shift(self, periods: int = 1) -> "CycloneFrame":
        return self._frame._like(self._scatter(
            lambda v: CycloneSeries(v).shift(periods).values))

    def rank(self, method: str = "average") -> "CycloneFrame":
        return self._frame._like(self._scatter(
            lambda v: CycloneSeries(v).rank(method).values))

    def cumcount(self) -> CycloneSeries:
        out = np.empty(len(self._frame), dtype=np.int64)
        for pos_list in self._groups().values():
            out[np.asarray(pos_list)] = np.arange(len(pos_list))
        return CycloneSeries(out, index=self._frame._index)

    def ngroup(self) -> CycloneSeries:
        """Group number by SORTED key order (the pandas contract)."""
        order = self._groups()
        out = np.empty(len(self._frame), dtype=np.int64)
        for g, t in enumerate(sorted(order)):
            out[np.asarray(order[t])] = g
        return CycloneSeries(out, index=self._frame._index)

    def filter(self, func) -> "CycloneFrame":
        """Rows of groups where ``func(group_frame)`` is truthy, original
        row order (ref pandas groupby.filter)."""
        keep: list = []
        f = self._frame
        for pos_list in self._groups().values():
            pos = np.asarray(pos_list, dtype=np.int64)
            if func(f._take(pos)):
                keep.extend(pos_list)
        return f._take(np.asarray(sorted(keep), dtype=np.int64))

    def head(self, n: int = 5) -> "CycloneFrame":
        keep: list = []
        for pos_list in self._groups().values():
            keep.extend(pos_list[:n])
        return self._frame._take(np.asarray(sorted(keep), dtype=np.int64))


def _astype_pandas(arr: np.ndarray, dtype) -> np.ndarray:
    """One column cast with pandas semantics (ref pyspark/pandas/
    data_type_ops): float NaN/inf -> integer raises; object parses
    per-element; str stringifies everything (NaN -> 'nan')."""
    arr = np.asarray(arr)
    dt = np.dtype(dtype) if dtype not in (str, "str", "string") else None
    if dt is None or dt.kind in "US":
        out = np.empty(len(arr), dtype=object)
        null = _is_null(arr)
        for i, v in enumerate(arr):
            out[i] = v if null[i] else str(v)  # NaN survives str cast
        return out
    if dt.kind in "iu":
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            raise ValueError(
                "Cannot convert non-finite values (NA or inf) to integer")
        if arr.dtype == object:
            return np.array([int(v) for v in arr], dtype=dt)
        return arr.astype(dt)
    if dt.kind == "f" and arr.dtype == object:
        return np.array([np.nan if v is None else float(v) for v in arr],
                        dtype=dt)
    return arr.astype(dt)


# both freq alias generations: pandas<2.2 ("H","T","M","S") and >=2.2
# ("h","min","ME","s") spell the same rules
_FREQ_UNIT = {"S": "s", "T": "m", "MIN": "m", "H": "h", "D": "D",
              "W": "W", "M": "M", "ME": "M"}


def _parse_freq(freq: str):
    """'15T' -> (15, 'm'); bare letters default to multiplier 1."""
    i = 0
    while i < len(freq) and freq[i].isdigit():
        i += 1
    mult = int(freq[:i]) if i else 1
    unit = _FREQ_UNIT.get(freq[i:].upper())
    if unit is None:
        raise ValueError(f"unsupported freq {freq!r}")
    return mult, unit


def date_range(start=None, end=None, periods: Optional[int] = None,
               freq: str = "D") -> np.ndarray:
    """(ref pandas.date_range) — datetime64[ns] range from any two of
    start/end/periods. Calendar rules: W anchors on Sundays, M emits
    month ENDS, like pandas."""
    mult, unit = _parse_freq(freq)
    if start is None:
        if end is None or periods is None:
            raise ValueError(
                "date_range needs two of start/end/periods")
        if unit == "M":
            # anchor on the last month END on or before ``end``
            e_day = np.datetime64(end, "D")
            em = np.datetime64(end, "M")
            eom = (em + np.timedelta64(1, "M")).astype("M8[D]") \
                - np.timedelta64(1, "D")
            if eom > e_day:
                em = em - np.timedelta64(1, "M")
            months = em - np.arange(periods - 1, -1, -1) \
                * np.timedelta64(mult, "M")
            ends = (months + np.timedelta64(1, "M")).astype("M8[D]") \
                - np.timedelta64(1, "D")
            return ends.astype("M8[ns]")
        if unit == "W":
            e = np.datetime64(end, "D")
            dow = (e.astype(np.int64) + 3) % 7  # Mon=0
            last = e - np.timedelta64((int(dow) - 6) % 7, "D")
            step = np.timedelta64(7 * mult, "D")
            return (last - np.arange(periods - 1, -1, -1) * step
                    ).astype("M8[ns]")
        step = np.timedelta64(mult, unit)
        e = np.datetime64(end).astype("M8[ns]")
        return (e - np.arange(periods - 1, -1, -1) * step).astype("M8[ns]")
    if unit == "M":
        # month-end stamps: walk month starts, step back one day
        s = np.datetime64(start, "M")
        if periods is None:
            e = np.datetime64(end, "M")
            months = np.arange(s, e + np.timedelta64(1, "M"),
                               np.timedelta64(mult, "M"))
        else:
            months = s + np.arange(periods) * np.timedelta64(mult, "M")
        ends = (months + np.timedelta64(1, "M")).astype("M8[D]") \
            - np.timedelta64(1, "D")
        if end is not None and periods is None:
            ends = ends[ends <= np.datetime64(end, "D")]
        return ends.astype("M8[ns]")
    if unit == "W":
        # anchor each stamp on the Sunday >= start (pandas W = W-SUN)
        s = np.datetime64(start, "D")
        dow = (s.astype(np.int64) + 3) % 7  # Mon=0; 1970-01-01 Thursday=3
        first = s + np.timedelta64((6 - int(dow)) % 7, "D")
        step = np.timedelta64(7 * mult, "D")
        if periods is None:
            e = np.datetime64(end, "D")
            out = np.arange(first, e + np.timedelta64(1, "D"), step)
        else:
            out = first + np.arange(periods) * step
        return out.astype("M8[ns]")
    step = np.timedelta64(mult, unit)
    if periods is not None:
        s = np.datetime64(start).astype("M8[ns]")
        return (s + np.arange(periods) * step).astype("M8[ns]")
    s = np.datetime64(start).astype("M8[ns]")
    e = np.datetime64(end).astype("M8[ns]")
    return np.arange(s, e + np.timedelta64(1, "ns"), step).astype("M8[ns]")


class _Resampler:
    """Bucket rows by a floored/anchored datetime key and aggregate;
    empty bins materialize like pandas' resample output."""

    def __init__(self, ts: np.ndarray, cols: Dict[str, np.ndarray],
                 rule: str, index_name: str):
        self._ts = ts
        self._cols = cols
        self._rule = rule
        self._index_name = index_name

    def _bins(self):
        mult, unit = _parse_freq(self._rule)
        ts = self._ts
        if unit == "M":
            months = ts.astype("M8[M]")
            labels = ((months + np.timedelta64(1, "M")).astype("M8[D]")
                      - np.timedelta64(1, "D")).astype("M8[ns]")
            lo, hi = months.min(), months.max()
            all_m = np.arange(lo, hi + np.timedelta64(1, "M"))
            full = ((all_m + np.timedelta64(1, "M")).astype("M8[D]")
                    - np.timedelta64(1, "D")).astype("M8[ns]")
            return labels, full
        if unit == "W":
            days = ts.astype("M8[D]")
            dow = (days.astype(np.int64) + 3) % 7  # Mon=0
            labels = (days + ((6 - dow) % 7).astype("m8[D]")
                      ).astype("M8[ns]")
            full = np.arange(labels.min(), labels.max()
                             + np.timedelta64(1, "ns"),
                             np.timedelta64(7, "D").astype("m8[ns]"))
            return labels, full
        step = np.timedelta64(mult, unit).astype("m8[ns]")
        base = ts.astype(f"M8[{unit}]").astype("M8[ns]")
        if mult != 1:
            # pandas origin="start_day": bins anchor at the first
            # timestamp's MIDNIGHT, not at the first timestamp itself
            origin = ts.min().astype("M8[D]").astype("M8[ns]")
            base = origin + ((base - origin) // step) * step
        full = np.arange(base.min(), base.max() + np.timedelta64(1, "ns"),
                         step)
        return base, full

    def _agg(self, fn: str) -> "CycloneFrame":
        labels, full = self._bins()
        pos = {v: i for i, v in enumerate(full)}
        codes = np.array([pos[v] for v in labels], dtype=np.int64)
        n = len(full)
        out: Dict[str, np.ndarray] = {}
        for k, v in self._cols.items():
            v = np.asarray(v)
            if v.dtype == object:
                continue
            v = v.astype(np.float64)
            ok = ~np.isnan(v)  # pandas skipna: NaN rows leave their bin
            vc, cc = v[ok], codes[ok]
            csum = np.bincount(cc, weights=vc, minlength=n)
            cnt = np.bincount(cc, minlength=n).astype(np.float64)
            if fn == "sum":
                res = csum
            elif fn == "count":
                res = cnt
            elif fn == "mean":
                with np.errstate(invalid="ignore"):
                    res = csum / cnt
            else:  # min/max: empty bins -> NaN
                op = np.minimum if fn == "min" else np.maximum
                res_tmp = np.full(n, np.inf if fn == "min" else -np.inf)
                op.at(res_tmp, cc, vc)
                res = np.where(cnt > 0, res_tmp, np.nan)
            out[k] = res.astype(np.int64) if fn == "count" else res
        frame = CycloneFrame(out)
        frame._index = full
        frame._index_name = self._index_name
        return frame

    def sum(self):
        return self._agg("sum")

    def mean(self):
        return self._agg("mean")

    def count(self):
        return self._agg("count")

    def min(self):
        return self._agg("min")

    def max(self):
        return self._agg("max")


class CycloneFrame:
    """2-D table (ref: pyspark/pandas/frame.py)."""

    def __init__(self, data: Union[Dict[str, Any], "CycloneFrame"]):
        self._index: Optional[np.ndarray] = None  # None = positional
        self._index_name: str = "index"
        if isinstance(data, CycloneFrame):
            self._cols = {k: v.copy() for k, v in data._cols.items()}
            self._index = (None if data._index is None
                           else data._index.copy())
            self._index_name = data._index_name
            return
        cols = {}
        n = None
        for k, v in data.items():
            arr = v.values if isinstance(v, CycloneSeries) else np.asarray(v)
            if arr.dtype.kind in "US":
                arr = arr.astype(object)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(f"column {k!r}: length {len(arr)} != {n}")
            cols[k] = arr
        self._cols = cols

    # -- index ----------------------------------------------------------------
    @property
    def index(self) -> np.ndarray:
        return (np.arange(len(self)) if self._index is None
                else self._index)

    def set_index(self, col) -> "CycloneFrame":
        """(ref pandas set_index) — the column(s) become the row-label
        index and leave the data columns. A LIST of columns builds a
        MultiIndex analog: the index holds per-row label TUPLES and the
        index name is the level-name list (ref pyspark/pandas/indexes/
        multi.py — tuple-labelled rows over the same frame machinery)."""
        cols = [col] if isinstance(col, str) else list(col)
        out = CycloneFrame({k: v for k, v in self._cols.items()
                            if k not in cols})
        if len(cols) == 1:
            out._index = np.asarray(self._cols[cols[0]])
            out._index_name = cols[0]
        else:
            idx = np.empty(len(self), dtype=object)
            for i in range(len(self)):
                idx[i] = tuple(self._cols[c][i] for c in cols)
            out._index = idx
            out._index_name = list(cols)
        return out

    def reset_index(self, drop: bool = False) -> "CycloneFrame":
        cols: Dict[str, Any] = {}
        if not drop and self._index is not None:
            if isinstance(self._index_name, list):
                # MultiIndex: expand the label tuples back into columns
                for j, nm in enumerate(self._index_name):
                    cols[nm] = _narrow_object(np.array(
                        [t[j] for t in self._index], dtype=object))
            else:
                cols[self._index_name] = self._index
        cols.update(self._cols)
        return CycloneFrame(cols)

    def _like(self, cols: Dict[str, np.ndarray]) -> "CycloneFrame":
        """A frame with these columns and THIS frame's index metadata."""
        out = CycloneFrame(cols)
        out._index = self._index
        out._index_name = self._index_name
        return out

    def _take(self, pos: np.ndarray) -> "CycloneFrame":
        """Row subset by position, index carried along."""
        out = CycloneFrame({k: v[pos] for k, v in self._cols.items()})
        if self._index is not None:
            out._index = self._index[pos]
            out._index_name = self._index_name
        return out

    @property
    def loc(self) -> _LocIndexer:
        return _LocIndexer(self)

    @property
    def iloc(self) -> _ILocIndexer:
        return _ILocIndexer(self)

    def rolling(self, window: int,
                min_periods: Optional[int] = None) -> _FrameRolling:
        return _FrameRolling(self, window, min_periods)

    def expanding(self, min_periods: int = 1) -> _FrameRolling:
        return _FrameRolling(self, None, min_periods)

    # -- metadata --------------------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    @property
    def shape(self):
        n = len(next(iter(self._cols.values()))) if self._cols else 0
        return (n, len(self._cols))

    @property
    def dtypes(self) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._cols.items()}

    def __len__(self) -> int:
        return self.shape[0]

    # -- selection -------------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, str) or (np.isscalar(key) and key in self._cols):
            # (scalar non-string column labels come from unstack's levels)
            s = CycloneSeries(self._cols[key], key, index=self._index)
            s.index_name = self._index_name  # unstack needs the level names
            return s
        if isinstance(key, list):
            return self._like({k: self._cols[k] for k in key})
        if isinstance(key, CycloneSeries):  # boolean mask
            vals = np.asarray(key.values)
            has_null = (
                any(v is None or (isinstance(v, float) and np.isnan(v))
                    for v in vals)
                if vals.dtype == object
                else vals.dtype.kind == "f" and bool(np.isnan(vals).any()))
            if has_null:
                # pandas contract: a mask with nulls is an error, never a
                # silent truthy-NaN selection (NaN casts to True)
                raise ValueError(
                    "Cannot mask with non-boolean array containing NA / "
                    "NaN values")
            mask = vals.astype(bool)
            return self._take(np.nonzero(mask)[0])
        raise TypeError(f"cannot index with {type(key).__name__}")

    def __setitem__(self, key: str, value) -> None:
        arr = value.values if isinstance(value, CycloneSeries) else value
        arr = np.asarray(arr)
        if arr.ndim == 0:
            arr = np.full(len(self), arr[()])
        if self._cols and len(arr) != len(self):
            raise ValueError(
                f"column {key!r}: length {len(arr)} != {len(self)}")
        self._cols[key] = arr

    def assign(self, **kw) -> "CycloneFrame":
        out = CycloneFrame(self)
        for k, v in kw.items():
            out[k] = v(out) if callable(v) else v
        return out

    def drop(self, columns: Sequence[str]) -> "CycloneFrame":
        drop = set([columns] if isinstance(columns, str) else columns)
        return self._like({k: v for k, v in self._cols.items()
                           if k not in drop})

    def rename(self, columns: Dict[str, str]) -> "CycloneFrame":
        # _like: renaming columns must not drop the row index (pandas
        # keeps it; join/add_prefix/add_suffix all route through here)
        return self._like({columns.get(k, k): v
                           for k, v in self._cols.items()})

    # -- rows ------------------------------------------------------------------
    def head(self, n: int = 5) -> "CycloneFrame":
        # pandas semantics: negative n means "all but the last |n| rows"
        return self._take(np.arange(len(self))[:n])

    def tail(self, n: int = 5) -> "CycloneFrame":
        total = np.arange(len(self))
        return self._take(total[-n:] if n else total[:0])

    def sort_values(self, by, ascending: bool = True) -> "CycloneFrame":
        keys = [by] if isinstance(by, str) else list(by)
        order = np.lexsort([self._cols[k] for k in reversed(keys)])
        if not ascending:
            order = order[::-1]
        return self._take(order)

    def sort_index(self, ascending: bool = True) -> "CycloneFrame":
        order = np.argsort(self.index, kind="stable")
        if not ascending:
            order = order[::-1]
        return self._take(order)

    # -- missing data ----------------------------------------------------------
    def isna(self) -> "CycloneFrame":
        return self._like({k: _is_null(v) for k, v in self._cols.items()})

    def fillna(self, value) -> "CycloneFrame":
        return self._like({k: CycloneSeries(v).fillna(value).values
                           for k, v in self._cols.items()})

    def dropna(self) -> "CycloneFrame":
        if not self._cols:
            return CycloneFrame({})
        keep = ~np.logical_or.reduce([_is_null(v)
                                      for v in self._cols.values()])
        return self._take(np.nonzero(keep)[0])

    # -- combine ---------------------------------------------------------------
    def merge(self, other: "CycloneFrame", on=None, how: str = "inner",
              validate: Optional[str] = None, indicator: bool = False,
              left_on=None, right_on=None, left_index: bool = False,
              right_index: bool = False) -> "CycloneFrame":
        if left_index or right_index or left_on or right_on:
            # merge-on-index (ref pandas left_index/right_index and
            # pyspark.pandas frame.py merge): materialize each side's key
            # — index or named column — under a shared temp name, run the
            # column merge, then restore pandas' result-index rule (the
            # joined key labels the rows when an index participates)
            if on is not None:
                raise ValueError(
                    'Can only pass argument "on" OR index/left_on/'
                    "right_on combinations")
            key = "__cyclone_mkey"
            prov = "__cyclone_prov"
            lf = CycloneFrame(dict(self._cols))
            rf = CycloneFrame(dict(other._cols))
            if left_index:
                lf._cols = {key: np.asarray(self.index), **lf._cols}
            else:
                if left_on is None:
                    raise ValueError("must pass left_on or left_index")
                lf._cols = {key: lf._cols[left_on], **lf._cols}
                # pandas rule for a mixed merge: the COLUMN side's index
                # labels the result rows — carry it through the join
                lf._cols[prov] = np.asarray(self.index, dtype=object)
            if right_index:
                rf._cols = {key: np.asarray(other.index), **rf._cols}
            else:
                if right_on is None:
                    raise ValueError("must pass right_on or right_index")
                rf._cols = {key: rf._cols[right_on], **rf._cols}
                if prov not in lf._cols:
                    rf._cols[prov] = np.asarray(other.index, dtype=object)
            merged = lf.merge(rf, on=key, how=how, validate=validate,
                              indicator=indicator)
            labels = merged._cols.pop(key)
            carried = merged._cols.pop(prov, None)
            if left_index and right_index:
                merged._index = labels
                merged._index_name = (self._index_name
                                      if self._index is not None else
                                      other._index_name)
            else:
                # mixed: the column side's carried labels; rows that only
                # the INDEX side produced (outer/right unmatched) fall
                # back to the join-key label, which is all pandas has for
                # them either
                vals = np.asarray(carried)
                null = np.array([x is None or (isinstance(x, float)
                                               and np.isnan(x))
                                 for x in vals], dtype=bool)
                merged._index = _narrow_object(
                    np.where(null, labels.astype(object), vals))
                merged._index_name = (other._index_name if left_index
                                      else self._index_name)
            return merged
        from cycloneml_tpu.sql.session import CycloneSession
        keys = [on] if isinstance(on, str) else list(on)
        if validate is not None:
            # (ref pandas merge validate=): check key uniqueness per side
            # BEFORE joining; MergeError semantics via ValueError
            v = {"one_to_one": "1:1", "one_to_many": "1:m",
                 "many_to_one": "m:1", "many_to_many": "m:m"}.get(
                     validate, validate)
            if v not in ("1:1", "1:m", "m:1", "m:m"):
                raise ValueError(f"not a valid argument for validate: "
                                 f"{validate!r}")

            def _unique(frame):
                seen = set()
                for t in zip(*[frame._cols[k] for k in keys]):
                    if t in seen:
                        return False
                    seen.add(t)
                return True
            if v in ("1:1", "1:m") and not _unique(self):
                raise ValueError(
                    "Merge keys are not unique in left dataset; not a "
                    f"{validate} merge")
            if v in ("1:1", "m:1") and not _unique(other):
                raise ValueError(
                    "Merge keys are not unique in right dataset; not a "
                    f"{validate} merge")
        s = CycloneSession()
        lcols = dict(self._cols)
        rcols = dict(other._cols)
        if indicator:
            # provenance markers ride the join; NaN-ness afterwards says
            # which side produced each row (ref pandas indicator=True)
            lcols["__cyclone_lm"] = np.ones(len(self))
            rcols["__cyclone_rm"] = np.ones(len(other))
        left = s.create_data_frame(lcols)
        right = s.create_data_frame(rcols)
        out = left.join(right, on=on, how=how).to_dict()
        if indicator:
            lm = np.asarray(out.pop("__cyclone_lm"), dtype=np.float64)
            rm = np.asarray(out.pop("__cyclone_rm"), dtype=np.float64)
            out["_merge"] = np.where(
                np.isnan(lm), "right_only",
                np.where(np.isnan(rm), "left_only", "both")).astype(object)
        return CycloneFrame(out)

    def groupby(self, by) -> _GroupBy:
        return _GroupBy(self, [by] if isinstance(by, str) else list(by))

    # -- dtypes (ref pandas astype semantics; pyspark/pandas/data_type_ops)
    def astype(self, dtype) -> "CycloneFrame":
        """Single dtype or {column: dtype}; pandas cast rules — float
        NaN/inf to integer RAISES, object numeric strings parse, any
        value stringifies under str (NaN -> 'nan')."""
        spec = dtype if isinstance(dtype, dict) else {
            k: dtype for k in self._cols}
        cols = dict(self._cols)
        for k, dt in spec.items():
            cols[k] = _astype_pandas(cols[k], dt)
        return self._like(cols)

    # -- iteration protocols (ref pandas iterrows/itertuples) ------------
    def iterrows(self):
        """Yields ``(index_label, row Series)`` — the row rides as a
        Series over the column names, like pandas (and like pandas, this
        is the slow path; prefer columnar ops)."""
        labels = self.index
        names = list(self._cols)
        col_vals = [self._cols[c] for c in names]
        for i in range(len(self)):
            row = np.empty(len(names), dtype=object)
            for j, v in enumerate(col_vals):
                row[j] = v[i]
            yield labels[i], CycloneSeries(row, name=str(labels[i]),
                                           index=names)

    def itertuples(self, index: bool = True, name: str = "Cyclone"):
        """Yields namedtuples (positionally equal to pandas' — tuple
        comparison ignores the class name); invalid/duplicate field
        names fall back to positional via rename=True, as pandas does."""
        import collections
        names = list(self._cols)
        fields = (["Index"] if index else []) + names
        tup = collections.namedtuple(name, fields, rename=True)
        labels = self.index
        col_vals = [self._cols[c] for c in names]
        for i in range(len(self)):
            vals = [v[i] for v in col_vals]
            yield tup(*([labels[i]] + vals if index else vals))

    # -- resample (ref pandas resample; basic calendar rules) ------------
    def resample(self, rule: str, on: Optional[str] = None) -> "_Resampler":
        """Downsample over a datetime64 index (or the ``on`` column):
        supports the S/T(min)/H/D/W/M rules with multipliers. Like
        pandas, EMPTY bins appear in the result (sum/count 0, mean/min/
        max NaN)."""
        ts = (np.asarray(self._cols[on]) if on is not None
              else np.asarray(self.index))
        if ts.dtype.kind != "M":
            ts = ts.astype("M8[ns]")
        data_cols = {k: v for k, v in self._cols.items() if k != on}
        return _Resampler(ts.astype("M8[ns]"), data_cols, rule,
                          self._index_name if on is None else (on or
                                                               "index"))

    # -- stats -----------------------------------------------------------------
    def describe(self) -> "CycloneFrame":
        stats = ["count", "mean", "std", "min", "max"]
        out: Dict[str, list] = {"summary": stats}
        for k, v in self._cols.items():
            if v.dtype == object:
                continue
            s = CycloneSeries(v)
            out[k] = [s.count(), s.mean(), s.std(), s.min(), s.max()]
        return CycloneFrame({k: np.asarray(v, dtype=object)
                             if k == "summary" else np.asarray(v, dtype=float)
                             for k, v in out.items()})

    def apply(self, f: Callable, axis: int = 0):
        if axis == 0:
            return CycloneFrame({k: np.asarray(f(CycloneSeries(v, k)))
                                 for k, v in self._cols.items()})
        rows = self.to_records()
        return CycloneSeries(np.array([f(r) for r in rows]))

    # -- frame reductions (→ Series over the column labels) --------------
    def _reduce(self, fn: str, numeric_only: bool = False) -> CycloneSeries:
        names, vals = [], []
        for k, v in self._cols.items():
            if v.dtype == object:
                if numeric_only:
                    continue
                if fn in ("mean", "std", "var", "median"):
                    raise TypeError(
                        f"Could not convert column {k!r} to numeric for "
                        f"{fn} (pass numeric_only=True)")
            names.append(k)
            vals.append(getattr(CycloneSeries(v), fn)())
        return CycloneSeries(np.asarray(vals), fn,
                             index=np.array(names, dtype=object))

    def sum(self, numeric_only: bool = False):
        return self._reduce("sum", numeric_only)

    def mean(self, numeric_only: bool = False):
        return self._reduce("mean", numeric_only)

    def std(self, numeric_only: bool = False):
        return self._reduce("std", numeric_only)

    def var(self, numeric_only: bool = False):
        return self._reduce("var", numeric_only)

    def median(self, numeric_only: bool = False):
        return self._reduce("median", numeric_only)

    def min(self, numeric_only: bool = False):
        return self._reduce("min", numeric_only)

    def max(self, numeric_only: bool = False):
        return self._reduce("max", numeric_only)

    def nunique(self) -> CycloneSeries:
        return self._reduce("nunique")

    def any(self) -> CycloneSeries:
        return self._reduce("any")

    def all(self) -> CycloneSeries:
        return self._reduce("all")

    def idxmax(self) -> CycloneSeries:
        return CycloneSeries(
            np.array([CycloneSeries(v, k, index=self._index).idxmax()
                      for k, v in self._cols.items()], dtype=object),
            "idxmax", index=np.array(self.columns, dtype=object))

    def idxmin(self) -> CycloneSeries:
        return CycloneSeries(
            np.array([CycloneSeries(v, k, index=self._index).idxmin()
                      for k, v in self._cols.items()], dtype=object),
            "idxmin", index=np.array(self.columns, dtype=object))

    def quantile(self, q=0.5, numeric_only: bool = False):
        names = [k for k, v in self._cols.items()
                 if not (numeric_only and v.dtype == object)]
        if np.isscalar(q):
            return CycloneSeries(
                np.array([CycloneSeries(self._cols[k]).quantile(q)
                          for k in names]),
                q, index=np.array(names, dtype=object))
        # list of quantiles → a frame indexed by q (the pandas shape)
        out = CycloneFrame({
            k: np.array([CycloneSeries(self._cols[k]).quantile(x)
                         for x in q]) for k in names})
        out._index = np.asarray(q, dtype=np.float64)
        return out

    # -- elementwise / cumulative (column-at-a-time Series delegation) ----
    def _per_column(self, method: str, *a, **kw) -> "CycloneFrame":
        return self._like({
            k: getattr(CycloneSeries(v, k), method)(*a, **kw).values
            for k, v in self._cols.items()})

    def abs(self) -> "CycloneFrame":
        return self._per_column("abs")

    def round(self, decimals: int = 0) -> "CycloneFrame":
        return self._per_column("round", decimals)

    def clip(self, lower=None, upper=None) -> "CycloneFrame":
        return self._per_column("clip", lower, upper)

    def diff(self, periods: int = 1) -> "CycloneFrame":
        return self._per_column("diff", periods)

    def shift(self, periods: int = 1, fill_value=None) -> "CycloneFrame":
        return self._per_column("shift", periods, fill_value)

    def cumsum(self) -> "CycloneFrame":
        return self._per_column("cumsum")

    def cummax(self) -> "CycloneFrame":
        return self._per_column("cummax")

    def cummin(self) -> "CycloneFrame":
        return self._per_column("cummin")

    def rank(self, method: str = "average",
             ascending: bool = True) -> "CycloneFrame":
        return self._per_column("rank", method, ascending)

    def isin(self, values) -> "CycloneFrame":
        if isinstance(values, dict):
            return self._like({
                k: (CycloneSeries(v).isin(values[k]).values
                    if k in values else np.zeros(len(v), dtype=bool))
                for k, v in self._cols.items()})
        return self._per_column("isin", values)

    def where(self, cond, other=np.nan) -> "CycloneFrame":
        if isinstance(cond, CycloneFrame):
            return self._like({
                k: CycloneSeries(v).where(cond._cols[k], other).values
                for k, v in self._cols.items()})
        return self._per_column("where", cond, other)

    def mask(self, cond, other=np.nan) -> "CycloneFrame":
        if isinstance(cond, CycloneFrame):
            return self.where(
                cond._like({k: ~np.asarray(v, dtype=bool)
                            for k, v in cond._cols.items()}), other)
        c = np.asarray(cond.values if isinstance(cond, CycloneSeries)
                       else cond, dtype=bool)
        return self.where(~c, other)

    # -- ordering / dedup -------------------------------------------------
    def nlargest(self, n: int, columns) -> "CycloneFrame":
        keys = [columns] if isinstance(columns, str) else list(columns)
        key_arr = np.lexsort(
            [-self._cols[k].astype(np.float64) for k in reversed(keys)])
        return self._take(key_arr[:n])

    def nsmallest(self, n: int, columns) -> "CycloneFrame":
        keys = [columns] if isinstance(columns, str) else list(columns)
        key_arr = np.lexsort(
            [self._cols[k].astype(np.float64) for k in reversed(keys)])
        return self._take(key_arr[:n])

    def duplicated(self, subset=None, keep="first") -> CycloneSeries:
        cols = ([subset] if isinstance(subset, str) else list(subset)) \
            if subset is not None else self.columns
        return CycloneSeries(
            _duplicated_mask([self._cols[c] for c in cols], keep),
            index=self._index)

    def drop_duplicates(self, subset=None, keep="first") -> "CycloneFrame":
        m = ~self.duplicated(subset, keep).values
        return self._take(np.nonzero(m)[0])

    # -- reshaping --------------------------------------------------------
    def melt(self, id_vars=None, value_vars=None, var_name: str = "variable",
             value_name: str = "value") -> "CycloneFrame":
        """(ref pandas melt / pyspark.pandas frame.py melt) — wide→long."""
        ids = ([id_vars] if isinstance(id_vars, str) else list(id_vars)) \
            if id_vars is not None else []
        vals = ([value_vars] if isinstance(value_vars, str)
                else list(value_vars)) if value_vars is not None \
            else [c for c in self.columns if c not in ids]
        n = len(self)
        out: Dict[str, np.ndarray] = {}
        for c in ids:
            out[c] = np.tile(self._cols[c], len(vals))
        out[var_name] = np.repeat(np.array(vals, dtype=object), n)
        out[value_name] = _narrow_object(np.concatenate(
            [np.asarray(self._cols[c], dtype=object) for c in vals]))
        return CycloneFrame(out)

    def stack(self) -> CycloneSeries:
        """columns → innermost index level: a Series with (row_label,
        column) tuple index, in row-major order (pandas 3 future_stack
        semantics: NaNs are KEPT)."""
        labels = self.index
        names = self.columns
        idx = np.empty(len(self) * len(names), dtype=object)
        vals = np.empty(len(self) * len(names), dtype=object)
        p = 0
        for i in range(len(self)):
            for c in names:
                idx[p] = (labels[i], c)
                vals[p] = self._cols[c][i]
                p += 1
        return CycloneSeries(_narrow_object(vals), None, index=idx)

    @property
    def T(self) -> "CycloneFrame":
        return self.transpose()

    def transpose(self) -> "CycloneFrame":
        """Duplicate index labels cannot transpose — the columnar dict
        would silently overwrite one of them (pandas keeps both; an
        error beats silent row loss here)."""
        labels = self.index
        if len(set(map(_norm_key, labels))) != len(labels):
            raise ValueError(
                "cannot transpose a frame with duplicate index labels")
        rows = self.columns
        out = CycloneFrame({
            labels[j]: _narrow_object(
                np.array([self._cols[c][j] for c in rows], dtype=object))
            for j in range(len(self))})
        out._index = np.array(rows, dtype=object)
        return out

    def join(self, other: "CycloneFrame", how: str = "left",
             lsuffix: str = "", rsuffix: str = "") -> "CycloneFrame":
        """Index-on-index merge (ref pandas DataFrame.join)."""
        overlap = set(self.columns) & set(other.columns)
        if overlap and not (lsuffix or rsuffix):
            raise ValueError(
                f"columns overlap but no suffix specified: {sorted(overlap)}")
        lf = self.rename({c: c + lsuffix for c in overlap}) if overlap \
            else self
        rf = other.rename({c: c + rsuffix for c in overlap}) if overlap \
            else other
        return lf.merge(rf, left_index=True, right_index=True, how=how)

    def combine_first(self, other: "CycloneFrame") -> "CycloneFrame":
        """Label-aligned coalesce: self's non-null values win, holes fill
        from ``other``; result over the SORTED index/column union
        (pandas Index.union sorts)."""
        union = sorted(set(self.index) | set(other.index))
        cols = self.columns + [c for c in other.columns
                               if c not in self.columns]
        lpos = {k: i for i, k in enumerate(self.index)}
        rpos = {k: i for i, k in enumerate(other.index)}
        out: Dict[str, np.ndarray] = {}
        for c in cols:
            vals = np.empty(len(union), dtype=object)
            for i, lab in enumerate(union):
                v = None
                if c in self._cols and lab in lpos:
                    v = self._cols[c][lpos[lab]]
                if (v is None or (isinstance(v, float) and np.isnan(v))) \
                        and c in other._cols and lab in rpos:
                    v = other._cols[c][rpos[lab]]
                vals[i] = np.nan if v is None else v
            out[c] = _narrow_object(vals)
        res = CycloneFrame(out)
        if self._index is not None or other._index is not None:
            res._index = np.array(union, dtype=object)
            res._index_name = self._index_name
        return res

    def sample(self, n: Optional[int] = None, frac: Optional[float] = None,
               random_state: Optional[int] = None) -> "CycloneFrame":
        if n is None:
            # pandas default: ONE row when neither n nor frac is given
            n = 1 if frac is None else int(round(frac * len(self)))
        rng = np.random.RandomState(random_state)
        return self._take(rng.choice(len(self), size=n, replace=False))

    # -- small conveniences ----------------------------------------------
    def copy(self) -> "CycloneFrame":
        return CycloneFrame(self)

    def equals(self, other: "CycloneFrame") -> bool:
        if self.columns != other.columns or len(self) != len(other):
            return False
        if list(map(_norm_key, self.index)) != \
                list(map(_norm_key, other.index)):
            return False
        for k in self.columns:
            a, b = self._cols[k], other._cols[k]
            na, nb = _is_null(a), _is_null(b)
            if not np.array_equal(na, nb):
                return False
            if not all(x == y for x, y in zip(a[~na], b[~nb])):
                return False
        return True

    def pop(self, col: str) -> CycloneSeries:
        return CycloneSeries(self._cols.pop(col), col, index=self._index)

    def insert(self, loc: int, column: str, value) -> None:
        if column in self._cols:
            raise ValueError(f"cannot insert {column}, already exists")
        arr = np.asarray(value.values if isinstance(value, CycloneSeries)
                         else value)
        if self._cols and len(arr) != len(self):
            raise ValueError(
                f"column {column!r}: length {len(arr)} != {len(self)}")
        items = list(self._cols.items())
        items.insert(loc, (column, arr))
        self._cols = dict(items)

    def add_prefix(self, prefix: str) -> "CycloneFrame":
        return self.rename({c: prefix + c for c in self.columns})

    def add_suffix(self, suffix: str) -> "CycloneFrame":
        return self.rename({c: c + suffix for c in self.columns})

    def corr(self) -> "CycloneFrame":
        return self._pairwise_stat("corr")

    def cov(self) -> "CycloneFrame":
        return self._pairwise_stat("cov")

    def _pairwise_stat(self, fn: str) -> "CycloneFrame":
        """Pairwise-complete-observation corr/cov over numeric columns —
        each (i, j) cell drops only rows where THAT pair has a null,
        matching pandas."""
        names = [k for k, v in self._cols.items() if v.dtype != object]
        out = {k: np.empty(len(names)) for k in names}
        for i, a in enumerate(names):
            sa = CycloneSeries(self._cols[a])
            for j, b in enumerate(names):
                out[b][i] = 1.0 if (fn == "corr" and a == b) else \
                    getattr(sa, fn)(CycloneSeries(self._cols[b]))
        res = CycloneFrame(out)
        res._index = np.array(names, dtype=object)
        return res

    # -- bridges ---------------------------------------------------------------
    def to_records(self) -> List[Dict[str, Any]]:
        cols = self.columns
        return [{c: self._cols[c][i] for c in cols}
                for i in range(len(self))]

    def to_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._cols)

    def to_pandas(self):
        import pandas as pd
        pdf = pd.DataFrame({k: v for k, v in self._cols.items()})
        if self._index is not None:
            if isinstance(self._index_name, list):
                pdf.index = pd.MultiIndex.from_tuples(
                    list(self._index), names=self._index_name)
            else:
                pdf.index = pd.Index(self._index, name=self._index_name)
        return pdf

    @classmethod
    def from_pandas(cls, pdf) -> "CycloneFrame":
        return cls({c: pdf[c].to_numpy() for c in pdf.columns})

    def to_sql_df(self, session=None):
        from cycloneml_tpu.sql.session import CycloneSession
        return (session or CycloneSession()).create_data_frame(
            dict(self._cols))

    def __repr__(self):
        n, m = self.shape
        return f"CycloneFrame({n} rows x {m} cols: {self.columns})"


def read_csv(path: str, header: bool = True,
             delimiter: str = ",") -> CycloneFrame:
    from cycloneml_tpu.sql.session import CycloneSession
    return CycloneFrame(
        CycloneSession().read_csv(path, header, delimiter).to_dict())


def concat(frames: Sequence[CycloneFrame], axis: int = 0,
           ignore_index: bool = False) -> CycloneFrame:
    """(ref pandas concat) — axis=0 stacks rows over the column UNION
    (missing columns fill NaN/None); axis=1 joins columns positionally."""
    frames = list(frames)
    if not frames:
        return CycloneFrame({})
    if axis == 1:
        cols: Dict[str, np.ndarray] = {}
        for f in frames:
            for k, v in f._cols.items():
                name = k
                i = 1
                while name in cols:  # pandas keeps duplicates; we suffix
                    name = f"{k}_{i}"
                    i += 1
                cols[name] = v
        return CycloneFrame(cols)
    names: List[str] = []
    for f in frames:
        for k in f.columns:
            if k not in names:
                names.append(k)
    out: Dict[str, np.ndarray] = {}
    for k in names:
        parts = []
        for f in frames:
            if k in f._cols:
                parts.append(np.asarray(f._cols[k], dtype=object)
                             if any(k not in g._cols for g in frames)
                             else f._cols[k])
            else:
                parts.append(np.full(len(f), None, dtype=object))
        out[k] = np.concatenate(parts)
    res = CycloneFrame(out)
    if not ignore_index:
        res._index = np.concatenate([f.index for f in frames])
    return res


def pivot_table(frame: CycloneFrame, values: str, index: str, columns: str,
                aggfunc: str = "mean", margins: bool = False,
                margins_name: str = "All") -> CycloneFrame:
    """(ref pandas pivot_table / pyspark/pandas/frame.py pivot_table) — one
    output row per distinct ``index`` value, one column per distinct
    ``columns`` value, cells aggregated with ``aggfunc``.

    ``margins=True`` appends an ``All`` column (per-row aggregate over the
    raw records) and an ``All`` row (per-column aggregate), aggregated
    over the UNDERLYING rows — not over cell results — matching pandas."""
    if aggfunc not in ("mean", "sum", "min", "max", "count"):
        raise ValueError(f"unsupported aggfunc {aggfunc!r}")
    iv = np.asarray(frame._cols[index])
    cv = np.asarray(frame._cols[columns])
    vv = np.asarray(frame._cols[values], dtype=np.float64)
    # one factorized pass: flat group id = row_code * n_cols + col_code
    # (a per-cell boolean mask scan is O(rows * cells))
    rows, r_code = np.unique(iv, return_inverse=True)
    cols, c_code = np.unique(cv, return_inverse=True)
    n_cells = len(rows) * len(cols)
    flat = r_code * len(cols) + c_code
    # pandas skips NaN values: they contribute to neither sums nor counts
    ok = ~np.isnan(vv)
    flat, vv = flat[ok], vv[ok]
    counts = np.bincount(flat, minlength=n_cells).astype(np.float64)
    if aggfunc in ("mean", "sum", "count"):
        sums = np.bincount(flat, weights=vv, minlength=n_cells)
        counts_nan = np.where(counts > 0, counts, np.nan)
        cell = {"sum": sums, "count": counts_nan,
                "mean": np.divide(sums, counts,
                                  out=np.full(n_cells, np.nan),
                                  where=counts > 0)}[aggfunc]
        if aggfunc == "sum":
            cell = np.where(counts > 0, cell, np.nan)
    else:
        cell = np.full(n_cells, np.inf if aggfunc == "min" else -np.inf)
        (np.minimum if aggfunc == "min" else np.maximum).at(cell, flat, vv)
        cell = np.where(counts > 0, cell, np.nan)
    grid = cell.reshape(len(rows), len(cols))

    def _agg_flat(v, codes, n):
        cnt = np.bincount(codes, minlength=n).astype(np.float64)
        if aggfunc == "count":
            return np.where(cnt > 0, cnt, np.nan)
        if aggfunc in ("mean", "sum"):
            s = np.bincount(codes, weights=v, minlength=n)
            if aggfunc == "sum":
                return np.where(cnt > 0, s, np.nan)
            return np.divide(s, cnt, out=np.full(n, np.nan), where=cnt > 0)
        m = np.full(n, np.inf if aggfunc == "min" else -np.inf)
        (np.minimum if aggfunc == "min" else np.maximum).at(m, codes, v)
        return np.where(cnt > 0, m, np.nan)

    out_cols = {str(c): grid[:, j] for j, c in enumerate(cols)}
    out_rows = rows
    if margins:
        row_all = _agg_flat(vv, r_code[ok], len(rows))   # All column
        col_all = _agg_flat(vv, c_code[ok], len(cols))   # All row
        grand = _agg_flat(vv, np.zeros(len(vv), np.int64), 1)[0]
        out_cols = {k: np.concatenate([v, [col_all[j]]])
                    for j, (k, v) in enumerate(out_cols.items())}
        out_cols[margins_name] = np.concatenate([row_all, [grand]])
        out_rows = np.concatenate([rows.astype(object),
                                   np.array([margins_name], object)])
    # the index is attached directly — building it as a data column could
    # collide with a pivot column that stringifies to the same name
    res = CycloneFrame(out_cols)
    res._index = out_rows
    res._index_name = index
    return res



def melt(frame: CycloneFrame, id_vars=None, value_vars=None,
         var_name: str = "variable", value_name: str = "value"
         ) -> CycloneFrame:
    """Module-level twin of :meth:`CycloneFrame.melt` (ref pd.melt)."""
    return frame.melt(id_vars, value_vars, var_name, value_name)


def get_dummies(data, prefix: Optional[str] = None, prefix_sep: str = "_",
                dtype=bool) -> CycloneFrame:
    """One-hot encode (ref pd.get_dummies / pyspark.pandas namespace.py).

    A Series encodes to sorted-category indicator columns; a frame
    encodes every object column in place, keeping numeric columns."""
    if isinstance(data, CycloneSeries):
        vals = data.values
        cats = sorted(set(vals[~_is_null(vals)]))
        # pandas: a bare Series encodes to unprefixed category columns
        name = prefix if prefix is not None else ""
        cols = {}
        for c in cats:
            key = f"{name}{prefix_sep}{c}" if name else str(c)
            cols[key] = np.asarray(vals == c, dtype=dtype)
        return CycloneFrame(cols)
    # pandas column order: untouched columns first, then every object
    # column's dummies appended in original column order
    out: Dict[str, np.ndarray] = {
        k: v for k, v in data._cols.items() if v.dtype != object}
    for k, v in data._cols.items():
        if v.dtype == object:
            sub = get_dummies(CycloneSeries(v, k), prefix=prefix or k,
                              prefix_sep=prefix_sep, dtype=dtype)
            out.update(sub._cols)
    return data._like(out)


def cut(x, bins, labels=None, right: bool = True) -> CycloneSeries:
    """Fixed-width binning (ref pd.cut). ``labels=False`` → integer bin
    codes (−1 for out-of-range/NaN, pandas' NaN analog in code space);
    a label list maps codes onto it. Interval-object labels (pandas'
    default) are not materialized — pass labels explicitly."""
    v = np.asarray(x.values if isinstance(x, CycloneSeries) else x,
                   dtype=np.float64)
    if np.isscalar(bins):
        # pandas: interior edges split [lo, hi] EXACTLY; only the OPEN
        # boundary edge is nudged outward afterwards so the extreme
        # value lands in its bin (edges[0] for right-closed bins,
        # edges[-1] for left-closed)
        lo, hi = np.nanmin(v), np.nanmax(v)
        span = (hi - lo) or 1.0
        edges = np.linspace(lo, hi, int(bins) + 1)
        if right:
            edges[0] = lo - 0.001 * span
        else:
            edges[-1] = hi + 0.001 * span
    else:
        edges = np.asarray(bins, dtype=np.float64)
    codes = np.searchsorted(edges, v, side="left" if right else "right") - 1
    if right:
        # right-closed: x == left edge of bin 0 belongs to NO bin unless
        # the edge itself equals x (pandas half-open (a, b] intervals)
        codes = np.where(v == edges[0], -1, codes)
    codes = np.where(np.isnan(v) | (codes < 0) | (codes >= len(edges) - 1),
                     -1, codes).astype(np.int64)
    if labels is False or labels is None:
        return CycloneSeries(codes, getattr(x, "name", ""))
    lab = np.asarray(labels, dtype=object)
    if len(lab) != len(edges) - 1:
        raise ValueError(
            "Bin labels must be one fewer than the number of bin edges")
    out = np.where(codes >= 0, lab[np.clip(codes, 0, len(lab) - 1)], None)
    return CycloneSeries(out, getattr(x, "name", ""))


def qcut(x, q, labels=None, duplicates: str = "raise") -> CycloneSeries:
    """Quantile binning (ref pd.qcut): equal-count bins by sample
    quantiles; same label semantics as :func:`cut`. Duplicate quantile
    edges (heavily tied data) RAISE like pandas unless
    ``duplicates='drop'`` merges them."""
    v = np.asarray(x.values if isinstance(x, CycloneSeries) else x,
                   dtype=np.float64)
    qs = np.linspace(0, 1, q + 1) if np.isscalar(q) else np.asarray(q)
    edges = np.nanquantile(v, qs)
    if len(np.unique(edges)) != len(edges):
        if duplicates != "drop":
            raise ValueError(
                f"Bin edges must be unique: {edges!r}. You can drop "
                f"duplicate edges by setting the 'duplicates' kwarg")
        edges = np.unique(edges)
    edges[0] = edges[0] - 1e-9 * (abs(edges[0]) + 1)
    return cut(x, edges, labels=labels, right=True)


def crosstab(index, columns, rownames=None, colnames=None) -> CycloneFrame:
    """Frequency table of two label arrays (ref pd.crosstab): rows/cols
    sorted, int64 counts. Column labels keep their original type (an
    int-valued ``columns`` yields int column keys, as pandas does);
    ``colnames`` is carried as ``_columns_name`` (display metadata — the
    engine has no columns-index object to attach it to)."""
    iv = np.asarray(index.values if isinstance(index, CycloneSeries)
                    else index, dtype=object)
    cv = np.asarray(columns.values if isinstance(columns, CycloneSeries)
                    else columns, dtype=object)
    rows = sorted(set(iv))
    cols = sorted(set(cv))
    rpos = {r: i for i, r in enumerate(rows)}
    cpos = {c: j for j, c in enumerate(cols)}
    grid = np.zeros((len(rows), len(cols)), dtype=np.int64)
    for a, b in zip(iv, cv):
        grid[rpos[a], cpos[b]] += 1
    out = CycloneFrame({c: grid[:, j] for j, c in enumerate(cols)})
    out._index = _label_array(rows)
    out._index_name = (rownames[0] if rownames else
                       getattr(index, "name", "") or "row_0")
    out._columns_name = (colnames[0] if colnames else
                         getattr(columns, "name", "") or "col_0")
    return out
