"""pandas-style API over columnar batches.

Analog of the reference's pandas API on Spark (ref: python/pyspark/pandas/
— frame.py, series.py, groupby.py; SURVEY §2.5). The reference compiles
pandas idioms onto lazy Spark SQL plans because its data is distributed
JVM rows; here the host ETL tier is already columnar numpy, so the facade
evaluates eagerly and bridges to the plan-based ``sql.DataFrame`` (and on to
MLFrame/device tiers) when distribution matters. Coverage follows the
pandas-on-Spark core: selection/assignment, boolean masking, sort_values,
groupby-agg, merge, fillna/dropna/isna, describe, value_counts, reductions,
apply, to/from pandas — plus label indexes (set_index/reset_index,
loc/iloc, aligned Series arithmetic), rolling/expanding windows, the
.str/.dt accessors, concat/pivot_table, datetime ranges + resample,
merge-on-index, pandas-semantics astype, and iterrows/itertuples — and the
long-tail tranche: frame/series reductions, rank/quantile/corr/cov,
cum* ops, shift/diff/pct_change, where/mask/isin/clip, nlargest,
duplicated/drop_duplicates, melt/stack/transpose/join/combine_first,
groupby transform/shift/rank/cumcount/ngroup/filter/size, and
get_dummies/cut/qcut/crosstab.
"""

from cycloneml_tpu.pandas.frame import (CycloneFrame, CycloneSeries, concat,
                                        crosstab, cut, date_range,
                                        get_dummies, melt, pivot_table,
                                        qcut, read_csv)

__all__ = ["CycloneFrame", "CycloneSeries", "concat", "crosstab", "cut",
           "date_range", "get_dummies", "melt", "pivot_table", "qcut",
           "read_csv"]
