"""ModelServer: low-latency inference on the program-cache waist.

The training side compiles once per program identity and dispatches many
times; serving inherits exactly that discipline. Registration (never a
request) pays every compile: the server AOT-warms one predict program per
power-of-two row bucket through the shared
:class:`~cycloneml_tpu.parallel.collectives.BoundedProgramCache` idiom —
a module-level cache keyed by servable SIGNATURE holds the jitted kernel
(two models with the same shape share one program outright), and jit's
per-shape cache under it holds the per-bucket executables. A request's
life is: queue -> coalesce (batcher window) -> pad to bucket -> admission
check -> replay a warmed program -> split results. Steady-state compiles
are pinned to zero by the serving tests.

K homogeneous models register as a GANG: one vmapped program scores all K
per dispatch (the PR-4 stacked engine's serving-side life), so a model
zoo multiplies throughput, not compile count or dispatch overhead.

Observability: every request gets a ``serving`` span (queue/dispatch
phases), latency/throughput feed the MetricsRegistry (p50/p95/p99 via the
canonical summary path), and a rolled-up stats dict rides
``ServingStatsUpdated`` events into the status store (``/api/v1/serving``
and the web UI).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from cycloneml_tpu.parallel.collectives import BoundedProgramCache
from cycloneml_tpu.serving.batcher import (ModelLane, ServingError,
                                           ServingOverloaded)
from cycloneml_tpu.serving.buckets import bucket_sizes
from cycloneml_tpu.serving.servable import (
    GangServable, Servable, as_servable, linear_margins,
    quantized_linear_margins, serving_dtype, stacked_linear_margins,
    stacked_quantized_linear_margins,
)
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

# servable signature -> jitted predict kernel. Module-level like the
# collectives program cache: programs survive server restarts and are
# cleared with clear_program_cache() on mesh teardown.
_predict_programs = BoundedProgramCache(128)


class ModelServer:
    """Registry + micro-batcher + admission control over servable models.

    ``ctx`` (a CycloneContext) supplies conf, metrics registry and the
    listener bus; all three degrade gracefully when the server runs
    standalone (defaults conf, private registry, no events). Keyword
    overrides beat conf — tests and demos tune windows without touching
    global conf state.
    """

    def __init__(self, ctx=None, *, conf=None, max_batch: Optional[int] = None,
                 window_ms: Optional[float] = None, dtype=None,
                 max_queue: Optional[int] = None,
                 shed_after_ms: Optional[float] = None,
                 max_retries: Optional[int] = None, registry=None,
                 quantize: Optional[bool] = None):
        from cycloneml_tpu.conf import (
            SERVING_MAX_BATCH, SERVING_MAX_QUEUE, SERVING_MAX_RETRIES,
            SERVING_QUANTIZE, SERVING_SHED_AFTER_MS, SERVING_WINDOW_MS,
            CycloneConf,
        )
        if ctx is None:
            from cycloneml_tpu.context import active_context
            ctx = active_context()
        self.ctx = ctx
        if conf is not None:
            self.conf = conf  # explicit conf wins (budget-guard tests)
        else:
            self.conf = ctx.conf if ctx is not None else CycloneConf()
        self.bus = ctx.listener_bus if ctx is not None else None
        if registry is not None:
            self.registry = registry
        elif ctx is not None:
            self.registry = ctx.metrics.registry
        else:
            from cycloneml_tpu.util.metrics import MetricsRegistry
            self.registry = MetricsRegistry()
        self.max_batch = int(max_batch if max_batch is not None
                             else self.conf.get(SERVING_MAX_BATCH))
        self.window_s = float(window_ms if window_ms is not None
                              else self.conf.get(SERVING_WINDOW_MS)) / 1e3
        self.max_queue = int(max_queue if max_queue is not None
                             else self.conf.get(SERVING_MAX_QUEUE))
        self.shed_after_s = float(
            shed_after_ms if shed_after_ms is not None
            else self.conf.get(SERVING_SHED_AFTER_MS)) / 1e3
        self.max_retries = int(max_retries if max_retries is not None
                               else self.conf.get(SERVING_MAX_RETRIES))
        self.dtype = (np.dtype(dtype) if dtype is not None
                      else serving_dtype(self.conf))
        # quantized predict tier: fp8 coefficient codes + per-row scales
        # (docs/serving.md) — smaller per-bucket peaks, so the admission
        # path fits more gang models under the same budgetFraction
        self.quantize = bool(quantize if quantize is not None
                             else self.conf.get(SERVING_QUANTIZE))
        self._lanes: Dict[str, ModelLane] = {}
        # names whose warm-up is in flight: _install releases the lock
        # during the (slow) AOT warm-up, so the duplicate-name check must
        # cover in-progress registrations too, not just finished ones
        self._registering: set = set()
        self._lock = threading.Lock()
        self._stats_last = 0.0
        self._stopped = False

    # -- program cache ----------------------------------------------------------

    def _program_for(self, servable: Union[Servable, GangServable]):
        """One jitted kernel per (gang?, dtype, quantized?) — shapes (and
        therefore buckets) live in jit's own cache below this key, so the
        ledger of real XLA compiles is ``program._cache_size()``."""
        import jax
        is_gang = isinstance(servable, GangServable)
        key = ("serving.linear_margins", is_gang, self.dtype.str,
               self.quantize)
        prog = _predict_programs.get(key)
        if prog is None:
            if self.quantize:
                kernel = (stacked_quantized_linear_margins if is_gang
                          else quantized_linear_margins)
            else:
                kernel = (stacked_linear_margins if is_gang
                          else linear_margins)
            prog = jax.jit(kernel)
            _predict_programs.put(key, prog)
        return prog

    # -- registration -----------------------------------------------------------

    def register(self, name: str, model: Any) -> Dict[str, Any]:
        """Adapt + AOT-warm ``model`` under ``name``. Every shape bucket
        compiles here (or proves already cached); returns the entry's
        stats, including the compile ledger."""
        return self._install(name, as_servable(model))

    def register_gang(self, name: str, models: Sequence[Any]
                      ) -> Dict[str, Any]:
        """Register K homogeneous models as ONE vmapped program.
        ``predict`` on a gang returns a list of K per-model results."""
        gang = GangServable([as_servable(m) for m in models])
        return self._install(name, gang)

    def _install(self, name: str, servable) -> Dict[str, Any]:
        with self._lock:
            if self._stopped:
                raise ServingError("model server is stopped", status=503)
            if name in self._lanes or name in self._registering:
                raise ValueError(f"model {name!r} already registered")
            self._registering.add(name)
            lane = ModelLane(name, servable, self)
        try:
            t0 = time.perf_counter()
            lane.warm_up()
            logger.info(
                "serving: registered %r (%s, d=%d): %d buckets warmed, %d "
                "compiles, %.1f ms", name,
                "gang[%d]" % servable.n_models if lane.is_gang else "serial",
                servable.n_features, len(lane.buckets), lane.compiles,
                (time.perf_counter() - t0) * 1e3)
            with self._lock:
                # re-check under the lock: stop() may have run while the
                # (slow, unlocked) warm-up was in flight — installing now
                # would leave a live worker on a "stopped" server
                if self._stopped:
                    raise ServingError("model server stopped during "
                                       "registration", status=503)
                lane.start()
                self._lanes[name] = lane
        finally:
            with self._lock:
                self._registering.discard(name)
        self._post_stats(force=True)
        return lane.stats()

    # -- request path -----------------------------------------------------------

    def predict(self, name: str, x, timeout: Optional[float] = None):
        """Score ``x`` (row vector or (n, d) matrix) against ``name``.

        Blocks until the micro-batcher answers; requests larger than
        ``maxBatch`` rows split into maxBatch-row sub-requests and
        reassemble transparently. Serial models return an (n,) prediction
        array; gangs return a list of K per-model arrays.
        """
        lane = self._lane(name)
        x2 = np.asarray(x, dtype=self.dtype)
        if x2.ndim == 1:
            # a single feature row — except a 0-length 1-D array, which is
            # how an empty wire payload (rows: []) arrives: that is an
            # empty REQUEST, not a d=0 row
            x2 = (x2.reshape(0, lane.servable.n_features) if x2.size == 0
                  else x2[None, :])
        if x2.ndim != 2 or x2.shape[1] != lane.servable.n_features:
            raise ValueError(
                f"model {name!r} expects (n, {lane.servable.n_features}) "
                f"features, got {x2.shape}")
        if x2.shape[0] == 0:
            empty = np.zeros((0,), dtype=np.float64)
            return ([empty] * lane.servable.n_models if lane.is_gang
                    else empty)
        futures = []
        try:
            for i in range(0, x2.shape[0], self.max_batch):
                futures.append(lane.submit(x2[i:i + self.max_batch]))
        except ServingError as e:
            # shed the whole request as a unit: a sibling chunk that hit
            # backpressure must not leave earlier chunks burning device
            # time on results the caller will never read
            for f in futures:
                lane.try_cancel(f)
            if isinstance(e, ServingOverloaded):
                # backpressure shed: freeze the flight-recorder window
                # (throttled) so a 503 burst is diagnosable after the fact
                from cycloneml_tpu.observe import flight
                flight.trigger("serving.shed", model=name)
            raise
        if timeout is None:
            # worst honest wait: window + shed patience + dispatch slack
            # per sub-request — a hung future is a bug, not a wait
            timeout = (self.window_s + self.shed_after_s
                       + 30.0) * len(futures)
        # ONE total deadline: an explicit timeout=5 means the caller gets
        # an answer (or a 504) within ~5 s, not 5 s per chunk
        deadline = time.monotonic() + timeout
        parts = []
        try:
            for f in futures:
                parts.append(f.result(
                    timeout=max(0.0, deadline - time.monotonic())))
        except BaseException as e:
            # one chunk failed: the caller gets nothing, so still-queued
            # siblings must not burn dispatches (same unwind as the
            # submit-time backpressure path)
            for f in futures:
                if not f.done():
                    lane.try_cancel(f)
            import concurrent.futures as _cf
            if isinstance(e, _cf.TimeoutError):
                raise ServingError(
                    f"model {name!r} request timed out after {timeout:.1f}s",
                    status=504, cause=e) from e
            raise
        if lane.is_gang:
            if len(parts) == 1:
                return parts[0]
            return [np.concatenate([p[k] for p in parts])
                    for k in range(lane.servable.n_models)]
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def _lane(self, name: str) -> ModelLane:
        with self._lock:
            lane = self._lanes.get(name)
        if lane is None:
            raise KeyError(
                f"no model {name!r} registered (have: "
                f"{sorted(self._lanes) or 'none'})")
        return lane

    # -- introspection ----------------------------------------------------------

    @property
    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._lanes)

    def n_features(self, name: str) -> int:
        return self._lane(name).servable.n_features

    def compile_counts(self) -> Dict[str, int]:
        """Per-model XLA compiles paid at registration — the serving
        tests pin this == the bucket count (and flat thereafter)."""
        with self._lock:
            return {n: lane.compiles for n, lane in self._lanes.items()}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lanes = dict(self._lanes)
        models = {n: lane.stats() for n, lane in lanes.items()}
        totals = {k: sum(m[k] for m in models.values())
                  for k in ("requests", "rows", "batches", "shed",
                            "retries", "compiles", "coalesced")}
        totals["models"] = len(models)
        totals["buckets"] = len(bucket_sizes(self.max_batch))
        return {"models": models, "totals": totals,
                "maxBatch": self.max_batch,
                "windowMs": self.window_s * 1e3,
                "dtype": self.dtype.name,
                "quantize": self.quantize}

    def _post_stats(self, force: bool = False) -> None:
        """Fold the rolled-up stats into the status store via the event
        bus, throttled so a hot serving loop does not flood the journal."""
        if self.bus is None:
            return
        now = time.monotonic()
        if not force and now - self._stats_last < 0.5:
            return
        self._stats_last = now
        from cycloneml_tpu.util.events import ServingStatsUpdated
        try:
            self.bus.post(ServingStatsUpdated(stats=self.stats()))
        except Exception:
            pass  # a stopped bus must not fail the dispatch path

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.stop()
        self._post_stats(force=True)

    def __enter__(self) -> "ModelServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
