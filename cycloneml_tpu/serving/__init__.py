"""Low-latency model serving on the program-cache waist.

The training subsystems compile once and dispatch many times; this
package gives inference the same discipline (ROADMAP open item 4, built
the way Clipper structured serving — Crankshaw et al., NSDI 2017):

- :mod:`~cycloneml_tpu.serving.servable` — the model-abstraction layer:
  fitted estimators (and K-model gangs, via the PR-4 vmap idiom) behind
  one device-kernel + host-postprocess interface.
- :mod:`~cycloneml_tpu.serving.buckets` — power-of-two padded shape
  buckets; registration warm-up pays every compile, requests never do.
- :mod:`~cycloneml_tpu.serving.batcher` — Clipper-style latency-bounded
  micro-batching, admission control against the PR-5 HBM accounting,
  chaos-instrumented dispatch (``serving.dispatch``).
- :mod:`~cycloneml_tpu.serving.server` — the ModelServer façade.
- :mod:`~cycloneml_tpu.serving.streaming` — featurize→predict→sink:
  score a streaming query (e.g. a Kafka source) through the same batcher.

See docs/serving.md for the architecture and conf keys.
"""

from cycloneml_tpu.serving.batcher import ServingError, ServingOverloaded
from cycloneml_tpu.serving.buckets import bucket_for, bucket_sizes, pad_rows
from cycloneml_tpu.serving.servable import (
    GangServable, Servable, as_servable, serving_dtype,
)
from cycloneml_tpu.serving.server import ModelServer
from cycloneml_tpu.serving.streaming import ScoringSink

__all__ = [
    "ModelServer", "ServingError", "ServingOverloaded", "Servable",
    "GangServable", "as_servable", "serving_dtype", "bucket_for",
    "bucket_sizes", "pad_rows", "ScoringSink",
]
