"""The model-abstraction layer: fitted estimators as servable programs.

Clipper's core move (Crankshaw et al., NSDI 2017) is a model-abstraction
layer between the serving frontend and the frameworks behind it: the
frontend batches and dispatches against one narrow interface, and each
model plugs in by describing how to compute its scores. Here the
interface is deliberately TPU-shaped: a servable exposes (a) device
parameters (arrays passed as program arguments, never closed over — so
one compiled program serves every model of the same signature) and (b) a
host-side postprocessing step that reuses the fitted model's OWN
reference numpy link/threshold code (``_raw_to_prediction``), keeping
serving semantics bit-compatible with ``model.predict``.

The device kernel computes linear margins as a broadcast-multiply-reduce
(``sum(x[:, None, :] * coef[None, :, :], -1)``) rather than a ``dot``:
each row's reduction is then independent of the batch dimension, so XLA
produces bitwise-identical per-row results in EVERY shape bucket —
zero-padding is numerically invisible, which the bucket-parity tests pin.
A gang of K homogeneous servables stacks its parameters on a leading
model axis and runs the vmapped twin of the same kernel: ONE program, K
models, per-row results bitwise-equal to K serial dispatches (the PR-4
stacked engine's serving-side life).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def serving_dtype(conf=None):
    """Resolve ``cyclone.serving.dtype``: 'auto' means the accumulator
    tier (float64 under jax x64, else float32). Request batches never ride
    the bf16 data tier — serving is latency-bound, not bandwidth-bound.

    An explicit 'float64' without jax x64 downgrades to float32 with a
    warning: XLA would silently canonicalize every f64 argument to f32,
    so honoring the name while computing narrower would misreport the
    served precision (the same reasoning as ``compute_dtype``).
    """
    from cycloneml_tpu.conf import SERVING_DTYPE
    from cycloneml_tpu.dataset.instance import compute_dtype
    name = "auto"
    if conf is not None:
        name = str(conf.get(SERVING_DTYPE))
    if name == "auto":
        return np.dtype(compute_dtype())
    if name == "float64":
        try:
            import jax
            if not jax.config.jax_enable_x64:
                logger.warning(
                    "cyclone.serving.dtype=float64 requires jax x64 "
                    "(jax would canonicalize f64 inputs to f32 silently); "
                    "serving at float32")
                return np.dtype(np.float32)
        except Exception:
            pass
    return np.dtype(name)


def linear_margins(coef, icpt, x):
    """Device predict kernel: (Km, d), (Km,), (B, d) -> (B, Km) margins.

    Broadcast-multiply-reduce on purpose (NOT ``x @ coef.T``): XLA picks
    different gemm strategies per shape, so a dot's last-ulp results vary
    with the batch dimension — this form reduces each row independently,
    making bucket padding bitwise-neutral (pinned by the parity tests).
    The (B, Km, d) product never materializes; XLA fuses it into one pass.
    """
    import jax.numpy as jnp
    return jnp.sum(x[:, None, :] * coef[None, :, :], axis=-1) + icpt[None, :]


def stacked_linear_margins(coefs, icpts, x):
    """Gang kernel: (K, Km, d), (K, Km), (B, d) -> (K, B, Km) — the
    vmapped twin of :func:`linear_margins` over a leading model axis; one
    compiled program scores all K models of a gang."""
    import jax
    return jax.vmap(linear_margins, in_axes=(0, 0, None))(coefs, icpts, x)


def quantized_linear_margins(coef8, scale, icpt, x):
    """Quantized predict kernel (``cyclone.serving.quantize``): the
    coefficient tensor arrives as fp8 (e4m3) CODES plus a per-margin-row
    scale at serving dtype; dequantization is one elementwise multiply on
    the (Km, d) tensor — O(model), not O(batch) — fused into the same
    broadcast-multiply-reduce as :func:`linear_margins`. The per-row
    reduction stays independent of the batch dimension, so bucket padding
    remains bitwise-neutral (pinned by the quantized parity tests).
    Coefficient HBM per program: 1 byte/element instead of 4-8 — the
    admission-path win that lets the same budget admit more gang models.
    """
    import jax.numpy as jnp
    c = coef8.astype(x.dtype) * scale[:, None]
    return jnp.sum(x[:, None, :] * c[None, :, :], axis=-1) + icpt[None, :]


def stacked_quantized_linear_margins(coef8s, scales, icpts, x):
    """Gang twin of :func:`quantized_linear_margins`:
    (K, Km, d) codes, (K, Km) scales, (K, Km) icpts, (B, d) ->
    (K, B, Km)."""
    import jax
    return jax.vmap(quantized_linear_margins,
                    in_axes=(0, 0, 0, None))(coef8s, scales, icpts, x)


def _quantize_rows(coef: np.ndarray, icpt: np.ndarray, dtype):
    """Per-margin-row fp8 quantization of a coefficient tensor: codes at
    e4m3, scales at the serving dtype. Works on (Km, d) (serial) and
    (K, Km, d) (gang) tensors — the scale is per LAST-BUT-ONE axis row."""
    import ml_dtypes
    from cycloneml_tpu.dataset.instance import FP8_MAX
    c = np.asarray(coef, dtype=np.float64)
    absmax = np.max(np.abs(c), axis=-1)
    scale = np.where(absmax > 0, absmax / FP8_MAX, 1.0)
    codes = (c / scale[..., None]).astype(ml_dtypes.float8_e4m3fn)
    return (codes, scale.astype(dtype, copy=False),
            np.asarray(icpt).astype(dtype, copy=False))


class Servable:
    """One fitted model behind the serving interface.

    ``raw_format`` maps device margins back into the model's raw-
    prediction convention so the model's own numpy postprocessing runs
    unchanged: ``pair`` (binary margin m -> raw (-m, m): logistic, SVC),
    ``identity`` (multinomial margins ARE the raw), ``scalar``
    (regression: the margin is the prediction).
    """

    def __init__(self, model: Any, coef: np.ndarray, icpt: np.ndarray,
                 raw_format: str):
        if raw_format not in ("pair", "identity", "scalar"):
            raise ValueError(f"unknown raw_format {raw_format!r}")
        self.model = model
        self._coef = np.atleast_2d(np.asarray(coef, dtype=np.float64))
        self._icpt = np.atleast_1d(np.asarray(icpt, dtype=np.float64))
        if self._icpt.shape[0] != self._coef.shape[0]:
            raise ValueError("coefficient rows and intercepts disagree")
        self.raw_format = raw_format

    @property
    def n_features(self) -> int:
        return self._coef.shape[1]

    @property
    def n_margins(self) -> int:
        return self._coef.shape[0]

    @property
    def signature(self) -> Tuple:
        """Homogeneity class: gangs require identical signatures, and the
        serving program cache keys on it (shapes below it are handled by
        jit's own per-shape cache)."""
        return (type(self.model).__name__, self.raw_format,
                self.n_margins, self.n_features)

    def params(self, dtype) -> Tuple[np.ndarray, np.ndarray]:
        """(coef, icpt) at the serving dtype — program ARGUMENTS, so every
        same-signature model shares one compiled program."""
        return (self._coef.astype(dtype, copy=False),
                self._icpt.astype(dtype, copy=False))

    def quantized_params(self, dtype):
        """(coef8, scale, icpt) for the quantized predict tier: e4m3
        codes with one scale per margin row (``scale_k = absmax_k /
        FP8_MAX``, 1.0 for an all-zero row — every code finite by
        construction), scale/icpt at the serving dtype. Intercepts stay
        wide: they are O(Km) and additive."""
        return _quantize_rows(self._coef, self._icpt, dtype)

    def margins_to_raw(self, margins: np.ndarray) -> np.ndarray:
        if self.raw_format == "pair":
            m = margins[:, 0]
            return np.stack([-m, m], axis=1)
        return margins

    def postprocess(self, margins: np.ndarray) -> np.ndarray:
        """Margins (n, Km) -> final predictions (n,), via the fitted
        model's own reference numpy link/threshold code."""
        if self.raw_format == "scalar":
            return margins[:, 0]
        return self.model._raw_to_prediction(self.margins_to_raw(margins))

    def host_margins(self, x: np.ndarray) -> np.ndarray:
        """Reference host-numpy margins (float64) — the parity baseline."""
        return x.astype(np.float64) @ self._coef.T + self._icpt[None, :]


class GangServable:
    """K homogeneous servables served from ONE vmapped program."""

    def __init__(self, members: Sequence[Servable]):
        members = list(members)
        if not members:
            raise ValueError("a gang needs at least one model")
        sig = members[0].signature
        for m in members[1:]:
            if m.signature != sig:
                raise ValueError(
                    f"gang members must be homogeneous: {m.signature} != "
                    f"{sig} (same model type, raw format, classes and "
                    f"feature count)")
        self.members: List[Servable] = members
        self._coefs = np.stack([m._coef for m in members])   # (K, Km, d)
        self._icpts = np.stack([m._icpt for m in members])   # (K, Km)

    @property
    def n_models(self) -> int:
        return len(self.members)

    @property
    def n_features(self) -> int:
        return self.members[0].n_features

    @property
    def signature(self) -> Tuple:
        return ("gang", self.n_models) + self.members[0].signature

    def params(self, dtype) -> Tuple[np.ndarray, np.ndarray]:
        return (self._coefs.astype(dtype, copy=False),
                self._icpts.astype(dtype, copy=False))

    def quantized_params(self, dtype):
        """(coef8s (K, Km, d), scales (K, Km), icpts (K, Km)) — the gang
        form of :meth:`Servable.quantized_params`."""
        return _quantize_rows(self._coefs, self._icpts, dtype)

    def postprocess(self, margins: np.ndarray) -> List[np.ndarray]:
        """Stacked margins (K, n, Km) -> per-model predictions
        [(n,), ...] through each member's own postprocessing."""
        return [m.postprocess(margins[k])
                for k, m in enumerate(self.members)]


def as_servable(model: Any) -> Servable:
    """Adapt a fitted estimator to the serving interface.

    Linear-form models are supported (their predict is one fused matvec —
    the latency-serving sweet spot): LogisticRegressionModel (binomial and
    multinomial), LinearSVCModel, LinearRegressionModel, and anything
    already wrapped as a :class:`Servable`.
    """
    if isinstance(model, (Servable, GangServable)):
        return model
    from cycloneml_tpu.ml.classification.linear_svc import LinearSVCModel
    from cycloneml_tpu.ml.classification.logistic_regression import (
        LogisticRegressionModel,
    )
    from cycloneml_tpu.ml.regression.linear_regression import (
        LinearRegressionModel,
    )
    if isinstance(model, LogisticRegressionModel):
        if model._is_multinomial:
            return Servable(model, model._coef, model._icpt, "identity")
        return Servable(model, model._coef[0], model._icpt[:1], "pair")
    if isinstance(model, LinearSVCModel):
        return Servable(model, model._coef, [model._icpt], "pair")
    if isinstance(model, LinearRegressionModel):
        return Servable(model, model._coef, [model._icpt], "scalar")
    raise TypeError(
        f"no servable adapter for {type(model).__name__}; supported: "
        f"LogisticRegressionModel, LinearSVCModel, LinearRegressionModel, "
        f"or a prebuilt Servable")
