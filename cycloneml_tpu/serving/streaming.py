"""Streaming scoring: featurize -> predict -> sink as ONE pipeline.

A structured-streaming query's sink receives micro-batches; wrapping the
sink routes every batch's feature columns through the model server's
micro-batcher before the rows land downstream — a Kafka (or file, or
rate) source scores through exactly the same bucketed, admission-guarded
dispatch path as online requests, and shows up in the same serving
metrics and spans. Idempotence carries over: a replayed batch id is
passed through to the inner sink, which already dedupes it.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from cycloneml_tpu.streaming.sinks import Sink


class ScoringSink(Sink):
    """Wrap an inner sink with model scoring.

    Each micro-batch's ``feature_cols`` assemble (in order) into the
    request matrix; predictions append as ``output_col`` (for a gang,
    ``output_col.0 .. output_col.K-1``, one column per member) and the
    widened batch forwards to ``inner``. Use with
    ``DataStreamWriter.sink_to``::

        sink = ScoringSink(server, "churn", ["f0", "f1"], MemorySink())
        query = df.write_stream.sink_to(sink).start()
    """

    def __init__(self, server, model: str, feature_cols: Sequence[str],
                 inner: Sink, output_col: str = "prediction"):
        self.server = server
        self.model = model
        self.feature_cols: List[str] = list(feature_cols)
        self.inner = inner
        self.output_col = output_col

    def add_batch(self, batch_id: int, batch, mode: str) -> None:
        cols = list(batch)
        n = len(batch[cols[0]]) if cols else 0
        out = dict(batch)
        if n:
            x = np.column_stack([np.asarray(batch[c], dtype=np.float64)
                                 for c in self.feature_cols])
        else:  # empty micro-batch still needs the output schema
            x = np.zeros((0, self.server.n_features(self.model)))
        preds = self.server.predict(self.model, x)
        if isinstance(preds, list):        # gang: one column per member
            for k in range(len(preds)):
                out[f"{self.output_col}.{k}"] = np.asarray(preds[k])
        else:
            out[self.output_col] = np.asarray(preds)
        self.inner.add_batch(batch_id, out, mode)
