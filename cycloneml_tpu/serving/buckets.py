"""Padded shape buckets: the compile-once contract of the model server.

XLA specializes every program to concrete shapes, so a naive server pays
a fresh trace + compile for every distinct request row count — tens of
seconds on TPU, fatal for a latency SLO. The fix (the same one every
production XLA server uses) is to quantize request shapes into a small
fixed set of buckets: power-of-two row counts from 1 up through
``cyclone.serving.maxBatch``, each batch zero-padded up to its bucket and
the padding rows sliced off after dispatch. Registration warm-up touches
every bucket, so the full compile bill is paid before the first request
arrives and the steady state never compiles.

Padding is numerically NEUTRAL by construction: the predict kernel
(:mod:`cycloneml_tpu.serving.servable`) reduces each row independently,
so a row's result is bitwise-identical whatever bucket carries it — the
bucket-parity tests pin this.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (int(n) - 1).bit_length() if n > 1 else 1


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """Every bucket the server compiles: 1, 2, 4, ... up through the next
    power of two >= ``max_batch`` (so a full ``max_batch``-row coalesced
    batch always has a bucket)."""
    top = next_pow2(max(1, int(max_batch)))
    out, b = [], 1
    while b <= top:
        out.append(b)
        b <<= 1
    return tuple(out)


def bucket_for(n_rows: int, max_batch: int) -> int:
    """The bucket an ``n_rows`` batch dispatches in. ``n_rows`` must not
    exceed the largest bucket (the batcher caps coalescing at maxBatch)."""
    if n_rows < 1:
        raise ValueError("empty batch has no bucket")
    b = next_pow2(n_rows)
    top = next_pow2(max(1, int(max_batch)))
    if b > top:
        raise ValueError(
            f"batch of {n_rows} rows exceeds the largest bucket {top} "
            f"(cyclone.serving.maxBatch)")
    return b


def pad_rows(x: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``x`` (n, d) up to (bucket, d). Returns ``x`` unchanged
    when it already fills the bucket exactly — no copy on the hot path."""
    n = x.shape[0]
    if n == bucket:
        return x
    if n > bucket:
        raise ValueError(f"{n} rows do not fit bucket {bucket}")
    out = np.zeros((bucket,) + x.shape[1:], dtype=x.dtype)
    out[:n] = x
    return out
