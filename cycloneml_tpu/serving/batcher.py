"""Dynamic micro-batching: one lane (queue + worker) per registered model.

Clipper's adaptive batching contract (Crankshaw et al., NSDI 2017 §4.3):
batch to amortize dispatch overhead, but bound the wait — a request waits
at most ``cyclone.serving.windowMs`` for co-riders before its batch
dispatches, and a batch never exceeds ``cyclone.serving.maxBatch`` rows.
Coalesced rows pad up to a power-of-two bucket (buckets.py) so the
steady state replays AOT-warmed programs and never compiles.

Before every dispatch the lane runs admission control against the PR-5
memory accounting: the bucket program's XLA-predicted peak HBM (harvested
at registration) plus live ``device.memory_stats`` occupancy, compared to
the ``cyclone.memory.budgetFraction`` budget. An over-budget batch is
requeued (backpressure) and re-checked each window until its oldest
request has waited ``cyclone.serving.shedAfterMs``, then shed with a
503-style :class:`~cycloneml_tpu.serving.ServingOverloaded` — the guard
path never raises ``MemoryBudgetError`` and never dispatches a program
predicted to OOM.

Dispatch rides the chaos harness (``serving.dispatch`` injection point):
transient failures retry with backoff up to ``cyclone.serving.maxRetries``;
permanent failures fail every request in the batch with a 5xx
:class:`~cycloneml_tpu.serving.ServingError`. Every outcome completes the
request futures — a fault can shed a request but never hang it.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from cycloneml_tpu.observe import attribution, costs, flight, skew, tracing
from cycloneml_tpu.serving.buckets import bucket_for, bucket_sizes, pad_rows
from cycloneml_tpu.serving.servable import GangServable
from cycloneml_tpu.util.logging import get_logger
from cycloneml_tpu.util.metrics import Histogram

logger = get_logger(__name__)


class ServingError(RuntimeError):
    """A request the server could not answer — carries an HTTP-shaped
    ``status`` (5xx) so wire frontends map it without string matching."""

    def __init__(self, msg: str, status: int = 500,
                 cause: Optional[BaseException] = None):
        super().__init__(msg)
        self.status = int(status)
        self.cause = cause


class ServingOverloaded(ServingError):
    """Load was shed: queue full, or admission control could not fit the
    dispatch within the memory budget before the shed deadline (503)."""

    def __init__(self, msg: str, cause: Optional[BaseException] = None):
        super().__init__(msg, status=503, cause=cause)


class _Request:
    __slots__ = ("x", "n", "future", "t_enq", "scope")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.n = x.shape[0]
        self.future: "Future" = Future()
        self.t_enq = time.perf_counter()
        # the SUBMITTING thread's attribution scope rides the request:
        # the lane worker that eventually dispatches it never sees the
        # caller's scope stack (same cross-thread capture as record_span)
        self.scope = attribution.current_scope()


class ModelLane:
    """Queue + worker thread + AOT-warmed bucket programs for ONE
    registered (model | gang) entry."""

    def __init__(self, name: str, servable, server):
        self.name = name
        self.servable = servable
        self.server = server
        self.is_gang = isinstance(servable, GangServable)
        self.buckets = bucket_sizes(server.max_batch)
        self.program = server._program_for(servable)
        # quantized tier: fp8 codes + per-row scales instead of wide
        # coefficients — the per-bucket program peak the admission path
        # accounts shrinks with them
        self._params = (servable.quantized_params(server.dtype)
                        if server.quantize
                        else servable.params(server.dtype))
        self._queue: "collections.deque[_Request]" = collections.deque()
        self._cv = threading.Condition()
        self._stop = False
        # per-lane seeded jitter (stable across processes — str hash is
        # salted): chaos replays of the retry backoff stay deterministic
        self._rng = random.Random(sum(name.encode()))
        self._thread: Optional[threading.Thread] = None
        # per-lane tallies (ints under the cv; scrape-side metrics live in
        # the server's shared MetricsRegistry)
        self.compiles = 0
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.coalesced = 0      # requests that shared a dispatch with >=1 other
        self.shed = 0
        self.retries = 0
        self.requeues = 0
        self.latency = Histogram(window=4096)   # seconds, request e2e
        self.pids = {}          # bucket -> costs program id (when harvested)
        # bucket -> BudgetVerdict from the FIRST admission check. The
        # predicted-peak side of a verdict is compile-time static, so
        # re-checks (the requeue loop runs one per window) reuse it and
        # only re-sample LIVE occupancy — one MemoryBudgetExceeded event
        # + warning per bucket, not one per 5 ms (the PR-5 cadence)
        self._verdicts = {}

    # -- registration-time AOT warm-up ---------------------------------------

    def _cache_size(self) -> Optional[int]:
        try:
            return int(self.program._cache_size())
        except Exception:
            return None

    def warm_up(self) -> None:
        """Touch every bucket once: the whole compile bill is paid here,
        before the first request. Each bucket that actually compiles (the
        per-shape jit cache missed — a same-signature model registered
        earlier may have paid already) bumps the compile ledger and gets a
        ``compile`` span; the steady state is pinned to add zero."""
        import jax
        d = self.servable.n_features
        tr = tracing.active()
        # guard_armed already includes "tracing active" in its policy
        harvest = costs.guard_armed(self.server.conf)
        for b in self.buckets:
            x0 = np.zeros((b, d), dtype=self.server.dtype)
            before = self._cache_size()
            with (tr.span("compile", f"serving/{self.name}", bucket=b)
                  if tr else tracing.NOOP_SPAN) as sp:
                out = self.program(*self._params, x0)
                jax.block_until_ready(out)
            after = self._cache_size()
            compiled = (after is None or before is None or after > before)
            if compiled:
                with self._cv:   # tallies are cv-guarded, warm-up included
                    self.compiles += 1
                self.server.registry.counter("serving.compiles").inc()
            sp.annotate(compiled=compiled)
            if harvest:
                # keyed on the servable SIGNATURE (not the lane name):
                # a second same-signature model must reuse the registry
                # entry, not re-pay analyze()'s AOT compile per bucket.
                # The quantized tier forks the key — its per-bucket peak
                # is the smaller one the admission path must account
                self.pids[b] = costs.ensure(
                    "serving", (self.servable.signature, b, str(x0.dtype),
                                self.server.quantize),
                    self.program, (*self._params, x0))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"cyclone-serve-{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
            worker = self._thread   # captured under the cv like the rest
        for r in pending:
            r.future.set_exception(
                ServingOverloaded(f"model server stopped while "
                                  f"{self.name!r} request was queued"))
        if worker is not None:
            worker.join(timeout=10)   # blocking join AFTER release

    # -- request side ---------------------------------------------------------

    def submit(self, x: np.ndarray) -> "Future":
        if x.shape[0] > self.server.max_batch:
            # a request _collect can never pop would wedge the lane in a
            # hot spin; ModelServer.predict pre-splits, so reaching this
            # is a direct-ModelLane caller's bug — fail it, loudly
            raise ValueError(
                f"request of {x.shape[0]} rows exceeds maxBatch "
                f"{self.server.max_batch}; split it (ModelServer.predict "
                f"does) or raise cyclone.serving.maxBatch")
        req = _Request(x)
        with self._cv:
            if self._stop:
                raise ServingError("model server is stopped", status=503)
            if len(self._queue) >= self.server.max_queue:
                self.shed += 1
                self.server.registry.counter("serving.shed").inc()
                attribution.charge_model(req.scope, self.name, sheds=1)
                raise ServingOverloaded(
                    f"{self.name!r} queue is full "
                    f"({self.server.max_queue} requests) — backpressure")
            self._queue.append(req)
            self._cv.notify_all()
        return req.future

    def try_cancel(self, fut: "Future") -> bool:
        """Remove a still-queued request and fail its future with a 503
        (ModelServer.predict unwinds a multi-chunk submission whose later
        chunk hit backpressure — already-queued siblings must not burn a
        dispatch computing results nobody will read). False when the
        request already left the queue (its dispatch is in flight)."""
        with self._cv:
            for r in self._queue:
                if r.future is fut:
                    self._queue.remove(r)
                    break
            else:
                return False
            self.shed += 1  # a 503 like every other shed path — counted
        self.server.registry.counter("serving.shed").inc()
        attribution.charge_model(r.scope, self.name, sheds=1)
        fut.set_exception(ServingOverloaded(
            f"{self.name!r}: sibling sub-request hit backpressure; "
            f"multi-chunk request shed as a unit"))
        return True

    # -- worker ----------------------------------------------------------------

    def _run(self) -> None:
        while True:
            got = self._collect()
            if got is None:
                return
            batch, rows = got
            if not batch:
                continue
            try:
                self._dispatch(batch, rows)
            except Exception as e:  # belt-and-braces: never hang a future
                logger.exception("serving lane %s: unexpected dispatch "
                                 "failure", self.name)
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(
                            ServingError(f"internal serving failure: {e}",
                                         status=500, cause=e))

    def _collect(self):
        """Assemble the next batch: up to maxBatch rows, waiting at most
        windowMs past the FIRST queued request's arrival (a worker that
        fell behind dispatches immediately — the window bounds added
        latency, it is never a mandatory sleep)."""
        with self._cv:
            while not self._queue and not self._stop:
                self._cv.wait(timeout=0.1)
            if self._stop:
                # anything that slipped in after stop() drained the queue
                # must still complete its future (the no-hang contract)
                leftovers = list(self._queue)
                self._queue.clear()
                for r in leftovers:
                    r.future.set_exception(ServingOverloaded(
                        f"model server stopped while {self.name!r} "
                        f"request was queued"))
                return None
            deadline = self._queue[0].t_enq + self.server.window_s
            batch: List[_Request] = []
            rows = 0
            while True:
                while (self._queue
                       and rows + self._queue[0].n <= self.server.max_batch):
                    r = self._queue.popleft()
                    batch.append(r)
                    rows += r.n
                if rows >= self.server.max_batch or self._stop:
                    break
                if self._queue:
                    break  # head does not fit this batch — dispatch now
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
            return batch, rows

    def _requeue_front(self, batch: List[_Request]) -> None:
        with self._cv:
            if not self._stop:
                for r in reversed(batch):
                    self._queue.appendleft(r)
                self.requeues += 1
                self.server.registry.counter("serving.requeued").inc()
                return
        # stop() already drained the queue — requeueing now would strand
        # these futures in a dead lane; give them the same 503 it gave
        # every other queued request
        for r in batch:
            r.future.set_exception(ServingOverloaded(
                f"model server stopped while {self.name!r} request "
                f"was queued"))

    # -- admission control -----------------------------------------------------

    def _admitted(self, bucket: int) -> bool:
        """Predict the dispatch's per-device peak HBM before running it.
        Unknown (guard unarmed, CPU cost gaps) admits — the guard refines
        behaviour when armed, it never blocks an unbudgeted deployment."""
        pid = self.pids.get(bucket)
        if pid is None:
            return True
        verdict = self._verdicts.get(bucket)
        if verdict is None:
            # never raises: serving degrades to queue/shed even under
            # cyclone.memory.budgetAction=raise — the 5xx IS the
            # escalation. First check per bucket only: the event +
            # warning it may post must not repeat every requeue window.
            verdict = costs.check_budget(pid, conf=self.server.conf,
                                         bus=self.server.bus,
                                         allow_raise=False)
            if verdict is not None:
                self._verdicts[bucket] = verdict
        if verdict is None:
            return True
        if verdict.exceeded:
            return False
        if verdict.budget_bytes and verdict.predicted_bytes:
            # hottest DEVICE, not the host average: a plain-jit dispatch
            # allocates on one device, and it is that device that OOMs
            live = costs.sample_device_peak()
            if live is not None and (
                    live + verdict.predicted_bytes > verdict.budget_bytes):
                return False
        return True

    def _shed_or_requeue(self, batch: List[_Request]) -> None:
        """Over-budget batch: shed members past the shed deadline with a
        503, requeue the rest (front of the queue) and wait one window for
        memory conditions to change."""
        now = time.perf_counter()
        keep: List[_Request] = []
        for r in batch:
            if now - r.t_enq >= self.server.shed_after_s:
                with self._cv:  # submit() bumps this tally under the cv too
                    self.shed += 1
                self.server.registry.counter("serving.shed").inc()
                attribution.charge_model(r.scope, self.name, sheds=1)
                r.future.set_exception(ServingOverloaded(
                    f"{self.name!r}: admission control predicts the "
                    f"dispatch exceeds the device memory budget "
                    f"(cyclone.memory.budgetFraction); request shed after "
                    f"{self.server.shed_after_s * 1e3:.0f} ms"))
            else:
                keep.append(r)
        shed_n = len(batch) - len(keep)
        if shed_n:
            # a shed burst is a flight-recorder trigger (throttled): the
            # ring shows what the lanes were doing when admission gave up
            flight.trigger("serving.shed", model=self.name, shed=shed_n)
        if keep:
            self._requeue_front(keep)
            with self._cv:
                if not self._stop:
                    self._cv.wait(timeout=max(self.server.window_s, 0.005))

    # -- dispatch ---------------------------------------------------------------

    def _dispatch(self, batch: List[_Request], rows: int) -> None:
        from cycloneml_tpu.parallel import faults
        from cycloneml_tpu.parallel.resilience import (
            backoff_delay, classify_failure,
        )
        t_batch = time.perf_counter()
        bucket = bucket_for(rows, self.server.max_batch)
        if not self._admitted(bucket):
            self._shed_or_requeue(batch)
            return
        x = (batch[0].x if len(batch) == 1
             else np.concatenate([r.x for r in batch], axis=0))
        xpad = pad_rows(x, bucket)
        tr = tracing.active()
        span = (tr.span("serving", self.name, rows=rows, bucket=bucket,
                        n_requests=len(batch),
                        program=self.pids.get(bucket, ""))
                if tr else tracing.NOOP_SPAN)
        attempt = 0
        with span:
            while True:
                try:
                    faults.inject("serving.dispatch", model=self.name,
                                  bucket=bucket)
                    out = self.program(*self._params, xpad)
                    # ONE host pull per dispatch (the JX001 discipline)
                    margins = np.asarray(out)
                    break
                except Exception as e:
                    kind = classify_failure(e)
                    if (kind == "transient"
                            and attempt < self.server.max_retries):
                        attempt += 1
                        with self._cv:   # tallies are cv-guarded
                            self.retries += 1
                        self.server.registry.counter("serving.retries").inc()
                        tracing.instant("retry", point="serving.dispatch",
                                        attempt=attempt, model=self.name)
                        time.sleep(backoff_delay(attempt - 1, base_s=0.01,
                                                 max_s=0.2,
                                                 rng=self._rng))
                        continue
                    status = 503 if kind == "transient" else 500
                    err = ServingError(
                        f"{self.name!r} dispatch failed ({kind}) after "
                        f"{attempt} retries: {e}", status=status, cause=e)
                    for r in batch:
                        r.future.set_exception(err)
                    self.server.registry.counter("serving.failed").inc(
                        len(batch))
                    return
        t_done = time.perf_counter()
        dispatch_s = t_done - t_batch
        # per-lane dispatch time feeds the straggler detector: one model
        # whose dispatches run long (cold bucket mix, contended device)
        # separates from the other lanes' rolling medians
        skew.observe("serving.dispatch", self.name, dispatch_s)
        if self.is_gang:
            margins = margins[:, :rows, :]     # (K, rows, Km)
        else:
            margins = margins[:rows, :]        # (rows, Km)
        # every tally/metric/span BEFORE any future completes: a caller
        # reading stats() the moment predict() returns must see this batch
        reg = self.server.registry
        with self._cv:
            self.requests += len(batch)
            self.rows += rows
            self.batches += 1
            if len(batch) > 1:
                self.coalesced += len(batch)
        reg.counter("serving.requests").inc(len(batch))
        reg.counter("serving.rows").inc(rows)
        reg.counter("serving.batches").inc()
        reg.timer("serving.dispatch").update(dispatch_s)
        reg.histogram("serving.batchRows").update(float(rows))
        reg.histogram("serving.batchRequests").update(float(len(batch)))
        for r in batch:
            e2e = t_done - r.t_enq
            self.latency.update(e2e)
            reg.timer("serving.latency").update(e2e)
            reg.timer("serving.queue").update(max(t_batch - r.t_enq, 0.0))
            # dispatch wall time split across co-riders by row share: the
            # per-scope servingSeconds sum equals the lane's dispatch time
            attribution.charge_model(r.scope, self.name, requests=1,
                                     rows=r.n,
                                     servingSeconds=dispatch_s * r.n / rows)
            if tr is not None:
                tr.record_span("serving", "request", t0=r.t_enq, t1=t_done,
                               parent=span.span_id, model=self.name,
                               rows=r.n, bucket=bucket,
                               queue_s=max(t_batch - r.t_enq, 0.0),
                               dispatch_s=dispatch_s)
        off = 0
        for r in batch:
            part = (margins[:, off:off + r.n, :] if self.is_gang
                    else margins[off:off + r.n, :])
            off += r.n
            try:
                r.future.set_result(self.servable.postprocess(part))
            except Exception as e:
                r.future.set_exception(ServingError(
                    f"postprocessing failed for {self.name!r}: {e}",
                    status=500, cause=e))
        self.server._post_stats()

    # -- introspection ----------------------------------------------------------

    def stats(self) -> dict:
        lat = self.latency.snapshot()
        with self._cv:
            # one cv acquisition for the whole tally row: the worker
            # updates these under the cv, and a scrape racing a dispatch
            # must not pair this batch's `rows` with last batch's
            # `batches` (torn rollup)
            tallies = {
                "compiles": self.compiles,
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "coalesced": self.coalesced,
                "shed": self.shed,
                "retries": self.retries,
                "requeues": self.requeues,
            }
        return {
            "buckets": list(self.buckets),
            "gang": self.servable.n_models if self.is_gang else 0,
            "quantized": bool(self.server.quantize),
            "nFeatures": self.servable.n_features,
            **tallies,
            "latencyMs": {k: (v * 1e3 if k != "count" else v)
                          for k, v in lat.items()},
        }
