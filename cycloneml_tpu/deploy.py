"""Standalone deploy mode — Master / Worker daemons.

Analog of the reference's standalone cluster manager (ref:
core/.../deploy/master/Master.scala, deploy/worker/Worker.scala,
deploy/Client.scala): a Master daemon tracks registered Workers over the
same TCP fabric the heartbeat/exchange layers use, and ``submit`` hands it
an application which the Master schedules onto Workers; each Worker
launches the driver/worker PROCESS with the ``multihost[...]`` environment
so the processes join one jax.distributed mesh (the executor-allocation
role of the reference's Master collapses into mesh formation — SURVEY
layer-map note).

Protocol: JSON lines over TCP. Worker -> Master: ``register``,
``heartbeat``, ``poll`` (fetch assigned launches), ``app_update``.
Client -> Master: ``submit``, ``status``. Master state (registered
workers, app history) persists to a JSON file so a restarted Master
recovers its cluster view (the recovery-file analog of
``FileSystemPersistenceEngine``), and HA mode runs multiple masters
contending for a file-lock leadership (the ZooKeeperLeaderElectionAgent
analog) with worker/client failover across the master group.
"""

from __future__ import annotations

import json
import os
import socketserver
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

WORKER_TIMEOUT_S = 60.0


def _send(addr: str, msg: dict, timeout: float = 30.0) -> dict:
    from cycloneml_tpu.util.tcp import check_not_challenge, connect_authed
    host, port = addr.rsplit(":", 1)
    with connect_authed(host, port, timeout=timeout) as s:
        s.sendall((json.dumps(msg) + "\n").encode())
        fh = s.makefile("r")
        line = fh.readline()
    check_not_challenge(line)
    return json.loads(line) if line.strip() else {}


# pool size a worker keeps pre-probed with the master; submits draw from it
COORD_PORT_POOL = 4
# a probed-but-unbound port goes stale as other processes bind; entries
# older than this are discarded rather than handed to a coordinator
COORD_PORT_TTL_S = 30.0


def _probe_free_ports(n: int) -> List[int]:
    """``n`` DISTINCT free ports on this machine — the coordinator-port
    probe shared with the multihost runtime (one implementation of the
    hold-all-sockets-open discipline; see bootstrap.probe_free_ports)."""
    from cycloneml_tpu.multihost.bootstrap import probe_free_ports
    return probe_free_ports(n)


class MasterDaemon:
    """Cluster manager: registration, liveness, app scheduling, status.

    HA mode (``ha_dir``): multiple masters contend for a file lock (the
    ZooKeeperLeaderElectionAgent analog — ref deploy/master/
    ZooKeeperLeaderElectionAgent.scala + FileSystemPersistenceEngine); the
    lock holder is LEADER and serves requests, standbys answer every
    request with a retryable ``not-leader`` error while waiting on the
    lock. A dead leader's lock releases with its process/close, the
    acquiring standby loads the shared recovery file, and workers fail
    over to it (their poll rotation + re-registration)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 state_path: Optional[str] = None,
                 ha_dir: Optional[str] = None):
        self._lock = threading.Lock()
        self._ha_dir = ha_dir
        self._lock_fh = None
        self._leader = ha_dir is None  # non-HA masters lead unconditionally
        if ha_dir is not None:
            os.makedirs(ha_dir, exist_ok=True)
            state_path = os.path.join(ha_dir, "master-state.json")
            self._lock_fh = open(os.path.join(ha_dir, "leader.lock"), "a+")
            self._try_acquire_leadership()
            self._elector = threading.Thread(
                target=self._election_loop, daemon=True,
                name="cyclone-master-elector")
        self._workers: Dict[str, dict] = {}   # id -> {addr?, last_seen, ...}
        self._apps: Dict[str, dict] = {}      # id -> {state, assignments...}
        self._launches: Dict[str, List[dict]] = {}  # worker id -> queue
        self._state_path = state_path
        self._rr = 0  # spreadOut rotation cursor
        self._load_state()
        master = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline()
                    if not line.strip():
                        return
                    reply = master._dispatch(json.loads(line))
                except Exception as e:  # malformed request must not kill us
                    reply = {"ok": False, "error": repr(e)}
                self.wfile.write((json.dumps(reply) + "\n").encode())

        from cycloneml_tpu.util.tcp import start_tcp_server
        self._server = start_tcp_server(host, port, Handler,
                                        "cyclone-master")
        self.address = (f"{host}:{self._server.server_address[1]}")
        if self._ha_dir is not None:
            self._elector.start()
        logger.info("cyclone master listening on %s (leader=%s)",
                    self.address, self._leader)

    # -- HA leader election (file-lock ZooKeeper analog) -------------------
    @property
    def is_leader(self) -> bool:
        with self._lock:   # flipped by the elector thread under the lock
            return self._leader

    def _try_acquire_leadership(self) -> None:
        import fcntl
        try:
            fcntl.flock(self._lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            self._leader = True
        except OSError:
            self._leader = False

    def _election_loop(self) -> None:
        import fcntl
        while not self._leader and not getattr(self, "_stopped", False):
            try:
                fcntl.flock(self._lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                time.sleep(0.2)
                continue
            with self._lock:
                # recover the dead leader's cluster view from the shared
                # recovery file BEFORE serving (ref Master.scala
                # ElectedLeader -> beginRecovery)
                self._load_state()
                self._leader = True
            logger.info("master %s elected leader", self.address)

    # -- persistence (FileSystemPersistenceEngine analog) ------------------
    def _load_state(self) -> None:
        if self._state_path and os.path.exists(self._state_path):
            with open(self._state_path, encoding="utf-8") as fh:
                st = json.load(fh)
            self._workers = st.get("workers", {})
            self._apps = st.get("apps", {})
            # a recovered worker is UNKNOWN until it re-registers (its
            # daemon may have died with the old master); recovered RUNNING
            # apps cannot complete — their launch queues were volatile —
            # so they fail explicitly rather than hang (the reference
            # master re-schedules; a lost app is surfaced, not stuck)
            for w in self._workers.values():
                w["state"] = "UNKNOWN"
            for a in self._apps.values():
                if a.get("state") == "RUNNING":
                    a["state"] = "FAILED"
                    a["reason"] = "master restarted mid-run"

    def _save_state(self) -> None:
        if not self._state_path or not self._leader:
            return  # a deposed/stopping master must not clobber the file
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"workers": self._workers, "apps": self._apps}, fh)
        os.replace(tmp, self._state_path)

    # -- protocol -----------------------------------------------------------
    def _dispatch(self, msg: dict) -> dict:
        kind = msg.get("kind")
        if not self.is_leader:   # locked read; released before re-entry
            # standby: every caller (worker poll rotation, HA-aware
            # clients) treats this as "try the next master"
            return {"ok": False, "error": "not-leader", "retryable": True}
        with self._lock:
            if kind == "register":
                wid = msg["worker_id"]
                now = time.time()
                self._workers[wid] = {"cores": int(msg.get("cores", 1)),
                                      "host": msg.get("host", "127.0.0.1"),
                                      "coord_ports":
                                          [[int(p), now] for p in
                                           msg.get("coord_ports", [])],
                                      "last_seen": now,
                                      "state": "ALIVE"}
                self._launches.setdefault(wid, [])
                self._save_state()
                return {"ok": True}
            if kind == "heartbeat":
                w = self._workers.get(msg["worker_id"])
                if w is None:
                    return {"ok": False, "error": "unregistered"}
                w["last_seen"] = time.time()
                w["state"] = "ALIVE"
                return {"ok": True}
            if kind == "poll":
                wid = msg["worker_id"]
                w = self._workers.get(wid)
                if w is None or w["state"] in ("UNKNOWN", "DEAD"):
                    # recovered/unknown workers must RE-register so the
                    # master learns their host and liveness afresh; an
                    # expired-DEAD worker that is polling again is alive —
                    # re-registration restores it (and refreshes its port
                    # pool, which went stale while it was away)
                    return {"ok": False, "error": "unregistered"}
                now = time.time()
                w["last_seen"] = now
                pool = self._fresh_ports(w, now)
                for p in msg.get("coord_ports", []):
                    if (len(pool) < COORD_PORT_POOL
                            and all(p != q[0] for q in pool)):
                        pool.append([int(p), now])
                self._retry_pending_places(now)
                q = self._launches.get(wid, [])
                out, self._launches[wid] = list(q), []
                # ask the worker to re-probe only when submits have drawn
                # the pool down or entries aged out (no bind/close per poll)
                return {"ok": True, "launches": out,
                        "need_ports": max(0, COORD_PORT_POOL - len(pool))}
            if kind == "app_update":
                app = self._apps.get(msg["app_id"])
                if app is not None:
                    if msg.get("attempt", 0) != app.get("attempt", 0):
                        # stale report from a killed earlier attempt —
                        # must not fail the relaunched app
                        return {"ok": True}
                    app["procs"][str(msg["proc_id"])] = {
                        "state": msg["state"],
                        "exit_code": msg.get("exit_code")}
                    if msg["state"] == "FAILED":
                        if (app["state"] == "RUNNING"
                                and app.get("launch_retries", 0) > 0
                                and not any(
                                    p["state"] == "FINISHED"
                                    for p in app["procs"].values())):
                            # relaunch ONCE with fresh coordinator ports:
                            # the probe-to-bind window means a pooled port
                            # can be taken by the time proc 0 binds it
                            # (r4 verdict item 10; ref Master.scala
                            # relaunchDriver supervise semantics). A
                            # failure after any proc FINISHED is app
                            # logic, not the bind race — no relaunch.
                            app["launch_retries"] -= 1
                            app["attempt"] = app.get("attempt", 0) + 1
                            for wid in app["workers"]:
                                self._launches.setdefault(wid, []).append(
                                    {"kill": msg["app_id"]})
                            app["procs"] = {}
                            rep = self._place(msg["app_id"])
                            if not rep.get("ok") and rep.get("retryable"):
                                # placement itself hit a transient (the
                                # port pool attempt 0 drew down refills at
                                # the next worker poll): park the relaunch
                                # instead of fail-fasting the mechanism
                                # built to survive transients
                                logger.info(
                                    "app %s relaunch placement deferred: "
                                    "%s", msg["app_id"], rep.get("error"))
                                app["place_deadline"] = \
                                    time.time() + WORKER_TIMEOUT_S
                                self._save_state()
                                return {"ok": True}
                            if rep.get("ok"):
                                logger.info(
                                    "app %s relaunched (attempt %d) after "
                                    "proc %s failed with exit %s",
                                    msg["app_id"], app["attempt"],
                                    msg["proc_id"], msg.get("exit_code"))
                                self._save_state()
                                return {"ok": True}
                            logger.warning(
                                "app %s relaunch placement failed: %s",
                                msg["app_id"], rep.get("error"))
                        # fail fast (ref Master removes the app on executor
                        # failure): siblings may hang on a dead coordinator
                        # — kill them rather than wait for all reports
                        if app["state"] != "FAILED":
                            app["state"] = "FAILED"
                            for wid in app["workers"]:
                                self._launches.setdefault(wid, []).append(
                                    {"kill": msg["app_id"]})
                    elif (len(app["procs"]) == app["n_procs"]
                          and all(p["state"] == "FINISHED"
                                  for p in app["procs"].values())):
                        app["state"] = "FINISHED"
                    self._save_state()
                return {"ok": True}
            if kind == "submit":
                return self._submit(msg)
            if kind == "status":
                self._expire()
                return {"ok": True, "workers": {
                    k: {"state": v["state"], "cores": v["cores"]}
                    for k, v in self._workers.items()},
                    "apps": {k: {"state": a["state"],
                                 "workers": a["workers"],
                                 "attempt": a.get("attempt", 0)}
                             for k, a in self._apps.items()}}
        return {"ok": False, "error": f"unknown kind {kind!r}"}

    def _retry_pending_places(self, now: float) -> None:
        """Relaunches whose placement hit a transient wait here (parked
        with ``place_deadline``); each worker poll — the event that
        refills port pools — retries them, failing the app only past the
        deadline."""
        for app_id, app in self._apps.items():
            deadline = app.get("place_deadline")
            if deadline is None or app["state"] != "RUNNING":
                continue
            rep = self._place(app_id)
            if rep.get("ok"):
                app.pop("place_deadline", None)
                logger.info("app %s deferred relaunch placed (attempt %d)",
                            app_id, app.get("attempt", 0))
                self._save_state()
            elif now > deadline:
                app.pop("place_deadline", None)
                app["state"] = "FAILED"
                logger.warning("app %s relaunch placement timed out: %s",
                               app_id, rep.get("error"))
                self._save_state()

    @staticmethod
    def _fresh_ports(w: dict, now: float) -> List[list]:
        """Drop aged-out pool entries in place and return the live pool."""
        pool = [e for e in w.setdefault("coord_ports", [])
                if now - e[1] <= COORD_PORT_TTL_S]
        w["coord_ports"] = pool
        return pool

    def _expire(self) -> None:
        now = time.time()
        for w in self._workers.values():
            if (w["state"] == "ALIVE"
                    and now - w["last_seen"] > WORKER_TIMEOUT_S):
                w["state"] = "DEAD"

    def _submit(self, msg: dict) -> dict:
        """Schedule an app onto n_procs ALIVE workers (round-robin, the
        reference's spreadOut placement); each launch carries the
        multihost coordinator address so the processes form ONE mesh."""
        app_id = f"app-{uuid.uuid4().hex[:8]}"
        self._apps[app_id] = {
            "state": "RUNNING", "n_procs": int(msg.get("n_procs", 1)),
            "workers": [], "procs": {}, "attempt": 0,
            # one automatic relaunch with FRESH ports covers the
            # probe-to-bind coordinator port race (verdict r4 item 10)
            "launch_retries": int(msg.get("launch_retries", 1)),
            "spec": {"app_path": msg["app_path"],
                     "args": msg.get("args", []),
                     "env": msg.get("env", {})}}
        rep = self._place(app_id)
        if not rep.get("ok"):
            del self._apps[app_id]
            return rep
        self._save_state()
        return {"ok": True, "app_id": app_id,
                "workers": self._apps[app_id]["workers"]}

    def _place(self, app_id: str) -> dict:
        """Pick workers + a coordinator port and queue the launches for
        the app's CURRENT attempt (first placement and relaunches share
        this — a relaunch draws a fresh port by construction)."""
        app = self._apps[app_id]
        spec = app["spec"]
        self._expire()
        n = app["n_procs"]
        alive = [k for k, v in self._workers.items() if v["state"] == "ALIVE"]
        if len(alive) < n:
            return {"ok": False,
                    "error": f"need {n} workers, have {len(alive)} alive"}
        # spreadOut rotation: consecutive submissions land on different
        # workers (ref Master.scala spreadOutApps)
        start = self._rr % len(alive)
        self._rr += 1
        chosen = (alive[start:] + alive[:start])[:n]
        # the coordinator lives on proc 0's HOST, so the port must be
        # probed THERE: workers keep a pool of pre-probed ports with the
        # master (register + poll top-ups); a submit draws one. A
        # master-side probe is meaningful ONLY for a worker on this same
        # machine — for a remote worker with a drained pool the submit is
        # rejected for retry rather than guessing a remote port.
        w0 = self._workers[chosen[0]]
        coord_host = w0.get("host", "127.0.0.1")
        pool = self._fresh_ports(w0, time.time())
        if pool:
            coord_port = pool.pop(0)[0]
        elif coord_host in ("127.0.0.1", "localhost",
                            self._server.server_address[0]):
            coord_port = _probe_free_ports(1)[0]
        else:
            return {"ok": False, "retryable": True,
                    "error": f"worker {chosen[0]} has no fresh probed "
                             f"coordinator port; retry after its next poll"}
        app["workers"] = chosen
        for i, wid in enumerate(chosen):
            self._launches.setdefault(wid, []).append({
                "app_id": app_id, "proc_id": i, "n_procs": n,
                "attempt": app.get("attempt", 0),
                "coordinator": f"{coord_host}:{coord_port}",
                "app_path": spec["app_path"],
                "args": spec["args"],
                "env": spec["env"]})
        return {"ok": True}

    def stop(self) -> None:
        # order matters for split-brain safety: drop leadership FIRST (so
        # in-flight handlers stop persisting — _save_state is
        # leader-guarded), stop serving, and only then release the flock
        # the next leader is waiting on. The flip takes the lock: it must
        # not interleave with an in-flight handler's locked persist, and
        # the elector's locked `_leader = True` must not be lost under it.
        self._stopped = True
        with self._lock:
            self._leader = False
        self._server.shutdown()
        self._server.server_close()
        if self._lock_fh is not None:
            try:
                self._lock_fh.close()  # releases the leader flock
            except OSError:
                pass


class WorkerDaemon:
    """Registers with the Master, polls for launches, runs app processes
    (ref Worker.scala ExecutorRunner/DriverRunner collapse into one
    process launch that joins the mesh)."""

    def __init__(self, master_addr: str, worker_id: Optional[str] = None,
                 cores: int = 1, poll_interval_s: float = 0.2,
                 host: str = "127.0.0.1"):
        # comma-separated list = HA master group: the worker rotates to the
        # next address when the current one is unreachable or answers
        # not-leader (ref Worker.scala MasterChanged handling)
        self.masters = [a.strip() for a in master_addr.split(",")
                        if a.strip()]
        self._mi = 0
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self.cores = cores
        self.host = host
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # app_id -> [Popen]: live processes only (pruned on exit)
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        self._register()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"cyclone-{self.worker_id}")
        self._thread.start()

    @property
    def master(self) -> str:
        return self.masters[self._mi % len(self.masters)]

    def _ask(self, msg: dict) -> dict:
        """Send to the current master, failing over through the HA group:
        an unreachable or standby (not-leader) master rotates to the next
        address and re-registers there."""
        for _ in range(len(self.masters)):
            try:
                rep = _send(self.master, msg)
            except OSError:
                self._mi += 1
                continue
            if not rep.get("ok") and rep.get("error") == "not-leader":
                self._mi += 1
                continue
            return rep
        return {"ok": False, "error": "no leader reachable"}

    def _register(self) -> None:
        # coordinator ports are probed HERE (where a proc-0 coordinator
        # would bind) so the master never guesses ports on a remote host
        rep = self._ask({"kind": "register",
                         "worker_id": self.worker_id,
                         "host": self.host, "cores": self.cores,
                         "coord_ports": _probe_free_ports(COORD_PORT_POOL)})
        if not rep.get("ok"):
            raise RuntimeError(f"registration failed: {rep}")

    def _loop(self) -> None:
        top_up: List[int] = []
        while not self._stop.is_set():
            try:
                rep = self._ask({"kind": "poll",
                                 "worker_id": self.worker_id,
                                 "coord_ports": top_up})
                top_up = []
                if not rep.get("ok") and rep.get("error") == "unregistered":
                    # a restarted master forgot us — re-register (the
                    # reference worker re-registers on MasterChanged)
                    self._register()
                # re-probe only when submits drained the master-side pool
                if rep.get("need_ports"):
                    top_up = _probe_free_ports(int(rep["need_ports"]))
                for launch in rep.get("launches", []):
                    if "kill" in launch:
                        self._kill(launch["kill"])
                    else:
                        self._launch(launch)
            except Exception as e:
                logger.warning("worker %s poll failed: %s", self.worker_id, e)
                # drop unsent probes: after an outage the master would stamp
                # them fresh on arrival, defeating COORD_PORT_TTL_S — the
                # next need_ports reply triggers a NEW probe instead
                top_up = []
            self._stop.wait(self.poll_interval_s)

    def _kill(self, app_id: str) -> None:
        with self._lock:
            procs = self._procs.pop(app_id, [])
        for p in procs:
            if p.poll() is None:
                p.terminate()

    def _launch(self, launch: dict) -> None:
        env = dict(os.environ)
        env.update(launch.get("env", {}))
        master_url = (
            f"multihost[{launch['coordinator']},{launch['n_procs']},"
            f"{launch['proc_id']}]")
        env["CYCLONE_MASTER_URL"] = master_url
        # Seed the normal conf channel too — OVERRIDING any forwarded
        # cyclone.master (e.g. the cyclone://host:port the client submitted
        # with) so an unmodified app calling CycloneContext.get_or_create()
        # joins the mesh, the way the reference worker rewrites spark.master
        # for launched processes.
        env["CYCLONE_CONF_cyclone__master"] = master_url
        env["CYCLONE_APP_ID"] = launch["app_id"]
        env["CYCLONE_PROC_ID"] = str(launch["proc_id"])
        proc = subprocess.Popen(
            [sys.executable, launch["app_path"], *launch.get("args", [])],
            env=env)
        with self._lock:
            self._procs.setdefault(launch["app_id"], []).append(proc)
        threading.Thread(target=self._wait, daemon=True,
                         args=(proc, launch)).start()

    def _wait(self, proc: subprocess.Popen, launch: dict) -> None:
        code = proc.wait()
        with self._lock:  # prune: a long-lived daemon must not accumulate
            live = self._procs.get(launch["app_id"], [])
            if proc in live:
                live.remove(proc)
            if not live:
                self._procs.pop(launch["app_id"], None)
        try:
            self._ask({
                "kind": "app_update", "app_id": launch["app_id"],
                "proc_id": launch["proc_id"],
                "attempt": launch.get("attempt", 0),
                "state": "FINISHED" if code == 0 else "FAILED",
                "exit_code": code})
        except Exception as e:
            logger.warning("app_update failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            procs = [p for ps in self._procs.values() for p in ps]
        for p in procs:
            if p.poll() is None:
                p.terminate()


def _send_ha(master_addr: str, msg: dict) -> dict:
    """Client-side send across a comma-separated HA master group: skip
    unreachable and standby (not-leader) masters."""
    addrs = [a.strip() for a in master_addr.split(",") if a.strip()]
    last: dict = {"ok": False, "error": "no master address"}
    for a in addrs:
        try:
            rep = _send(a, msg)
        except OSError as e:
            # unreachable during an election is as transient as a standby
            # reply — callers must retry either way (review r4: a plain
            # error here made retry behavior depend on address order)
            last = {"ok": False, "error": repr(e), "retryable": True}
            continue
        if not rep.get("ok") and rep.get("error") == "not-leader":
            last = rep
            continue
        return rep
    return last


def submit_app(master_addr: str, app_path: str, n_procs: int = 1,
               args: Optional[List[str]] = None,
               env: Optional[Dict[str, str]] = None,
               retries: int = 10, retry_wait_s: float = 0.5) -> str:
    """Client-side submit (ref deploy/Client.scala): returns the app id.

    Trace context propagates over this wire (the Dapper join,
    observe/collect.py): when this process runs a TraceCollector and a
    tracer, the submit opens a ``deploy`` span and injects the collector's
    launch env — trace id, the submit span's host-qualified id as remote
    parent, and the collector address — into the app env the Master
    schedules and the Worker hands to the launched process, whose
    CycloneContext then adopts the context and ships its spans back.
    Explicit ``env`` keys win over the injected ones.

    Retryable rejections (a remote worker's probed-port pool momentarily
    drained, an HA election in progress) are retried here so callers see
    them only when persistent."""
    from cycloneml_tpu.observe import collect, tracing
    submit_env = dict(env or {})
    tr = tracing.active()
    col = collect.active_collector()
    span = tr.span("deploy", f"submit {os.path.basename(app_path)}",
                   n_procs=n_procs) if tr is not None else tracing.NOOP_SPAN
    with span as sp:
        if col is not None:
            injected = col.launch_env(parent_span_id=sp.span_id)
            for k, v in injected.items():
                submit_env.setdefault(k, v)
        for attempt in range(retries + 1):
            rep = _send_ha(master_addr,
                           {"kind": "submit", "app_path": app_path,
                            "n_procs": n_procs, "args": args or [],
                            "env": submit_env})
            if rep.get("ok"):
                sp.annotate(app_id=rep["app_id"])
                return rep["app_id"]
            if not rep.get("retryable") or attempt == retries:
                raise RuntimeError(f"submit rejected: {rep.get('error')}")
            time.sleep(retry_wait_s)
    raise AssertionError("unreachable")


def app_status(master_addr: str, app_id: Optional[str] = None) -> dict:
    st = _send_ha(master_addr, {"kind": "status"})
    if not st.get("ok", True):
        # election in progress / no leader: surface a typed error the
        # wait loop can ride out instead of a KeyError
        raise IOError(f"no reachable leader: {st.get('error')}")
    if app_id is not None:
        return st["apps"].get(app_id, {"state": "UNKNOWN"})
    return st


def wait_for_app(master_addr: str, app_id: str,
                 timeout_s: float = 300.0) -> str:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            state = app_status(master_addr, app_id)["state"]
        except (IOError, OSError):
            time.sleep(0.2)  # HA election window: keep waiting
            continue
        if state in ("FINISHED", "FAILED"):
            return state
        time.sleep(0.2)
    raise TimeoutError(f"app {app_id} still running after {timeout_s}s")
