"""Typed configuration registry.

TPU-native equivalent of the reference's three-tier config system
(ref: core/src/main/scala/org/apache/spark/internal/config/ConfigBuilder.scala:183,
ConfigEntry.scala:74, SparkConf.scala): a typed ``ConfigEntry`` registry with
documentation, version, validators, defaults and fallbacks, plus a string-map
``CycloneConf`` seeded from defaults files / environment / programmatic sets.

Unlike the reference there is no separate SQLConf tier; session-mutable
entries are marked ``mutable=True`` instead.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Generic, Iterator, List, Optional, TypeVar

T = TypeVar("T")

_REGISTRY: Dict[str, "ConfigEntry"] = {}
_REGISTRY_LOCK = threading.Lock()


class ConfigEntry(Generic[T]):
    """A typed configuration entry (ref: ConfigEntry.scala:74)."""

    def __init__(
        self,
        key: str,
        default: Optional[T],
        value_type: type,
        doc: str = "",
        version: str = "0.1.0",
        validator: Optional[Callable[[T], bool]] = None,
        validator_msg: str = "",
        alternatives: Optional[List[str]] = None,
        fallback: Optional["ConfigEntry[T]"] = None,
        mutable: bool = False,
    ):
        self.key = key
        self.default = default
        self.value_type = value_type
        self.doc = doc
        self.version = version
        self.validator = validator
        self.validator_msg = validator_msg
        self.alternatives = alternatives or []
        self.fallback = fallback
        self.mutable = mutable
        with _REGISTRY_LOCK:
            if key in _REGISTRY:
                raise ValueError(f"Config entry already registered: {key}")
            _REGISTRY[key] = self

    def _convert(self, raw: Any) -> T:
        t = self.value_type
        if isinstance(raw, t) and not (t is int and isinstance(raw, bool)):
            return raw
        s = str(raw)
        if t is bool:
            if s.lower() in ("true", "1", "yes"):
                return True  # type: ignore[return-value]
            if s.lower() in ("false", "0", "no"):
                return False  # type: ignore[return-value]
            raise ValueError(f"{self.key}: cannot parse boolean from {raw!r}")
        if t is int:
            return int(s)  # type: ignore[return-value]
        if t is float:
            return float(s)  # type: ignore[return-value]
        if t is str:
            return s  # type: ignore[return-value]
        raise TypeError(f"{self.key}: unsupported config type {t}")

    def read_from(self, conf: "CycloneConf") -> T:
        for k in [self.key] + self.alternatives:
            if conf.contains_raw(k):
                v = self._convert(conf.get_raw(k))
                if self.validator is not None and not self.validator(v):
                    raise ValueError(
                        f"Invalid value {v!r} for {self.key}: {self.validator_msg}"
                    )
                return v
        if self.fallback is not None:
            return self.fallback.read_from(conf)
        if self.default is None:
            raise KeyError(f"Config {self.key} is not set and has no default")
        return self.default


class ConfigBuilder:
    """Fluent builder (ref: ConfigBuilder.scala:183)."""

    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._version = "0.1.0"
        self._validator: Optional[Callable] = None
        self._validator_msg = ""
        self._alternatives: List[str] = []
        self._mutable = False

    def doc(self, d: str) -> "ConfigBuilder":
        self._doc = d
        return self

    def version(self, v: str) -> "ConfigBuilder":
        self._version = v
        return self

    def with_alternative(self, key: str) -> "ConfigBuilder":
        self._alternatives.append(key)
        return self

    def check_value(self, fn: Callable, msg: str) -> "ConfigBuilder":
        self._validator = fn
        self._validator_msg = msg
        return self

    def mutable(self) -> "ConfigBuilder":
        self._mutable = True
        return self

    def _make(self, default, value_type, fallback=None) -> ConfigEntry:
        return ConfigEntry(
            self._key, default, value_type, self._doc, self._version,
            self._validator, self._validator_msg, self._alternatives,
            fallback, self._mutable,
        )

    def int_conf(self, default: Optional[int] = None) -> ConfigEntry[int]:
        return self._make(default, int)

    def float_conf(self, default: Optional[float] = None) -> ConfigEntry[float]:
        return self._make(default, float)

    def bool_conf(self, default: Optional[bool] = None) -> ConfigEntry[bool]:
        return self._make(default, bool)

    def str_conf(self, default: Optional[str] = None) -> ConfigEntry[str]:
        return self._make(default, str)

    def fallback_conf(self, parent: ConfigEntry) -> ConfigEntry:
        return self._make(None, parent.value_type, fallback=parent)


class CycloneConf:
    """String-keyed configuration map with typed reads.

    Mirrors SparkConf semantics (set/get/contains, env seeding via
    ``CYCLONE_*`` variables, clone) on top of the typed registry.
    """

    ENV_PREFIX = "CYCLONE_CONF_"

    def __init__(self, load_defaults: bool = True):
        self._settings: Dict[str, str] = {}
        self._lock = threading.Lock()
        if load_defaults:
            # CYCLONE_CONF_cyclone__eventLog__enabled=true → cyclone.eventLog.enabled
            # (case preserved; '__' separates dotted segments)
            for k, v in os.environ.items():
                if k.startswith(self.ENV_PREFIX):
                    key = k[len(self.ENV_PREFIX):].replace("__", ".")
                    self._settings[key] = v

    def set(self, key, value) -> "CycloneConf":
        k = key.key if isinstance(key, ConfigEntry) else key
        with self._lock:
            self._settings[k] = str(value)
        return self

    def set_if_missing(self, key, value) -> "CycloneConf":
        k = key.key if isinstance(key, ConfigEntry) else key
        with self._lock:
            self._settings.setdefault(k, str(value))
        return self

    def remove(self, key) -> "CycloneConf":
        k = key.key if isinstance(key, ConfigEntry) else key
        with self._lock:
            self._settings.pop(k, None)
        return self

    def contains_raw(self, key: str) -> bool:
        return key in self._settings

    def get_raw(self, key: str) -> str:
        return self._settings[key]

    def get(self, key, default: Any = None) -> Any:
        if isinstance(key, ConfigEntry):
            return key.read_from(self)
        entry = _REGISTRY.get(key)
        if entry is not None:
            # registered keys always get typed conversion + validation,
            # whether set or defaulted
            try:
                return entry.read_from(self)
            except KeyError:
                pass
        elif key in self._settings:
            return self._settings[key]
        if default is not None:
            return default
        raise KeyError(key)

    def get_all(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._settings)

    def clone(self) -> "CycloneConf":
        c = CycloneConf(load_defaults=False)
        c._settings = dict(self._settings)
        return c

    def __iter__(self) -> Iterator:
        return iter(self._settings.items())


def registered_entries() -> Dict[str, ConfigEntry]:
    with _REGISTRY_LOCK:
        return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Core entries (analog of internal/config/package.scala's centralized registry)
# ---------------------------------------------------------------------------

APP_NAME = ConfigBuilder("cyclone.app.name").doc("Application name.").str_conf("cyclone-app")

MASTER = (
    ConfigBuilder("cyclone.master")
    .doc("Mesh master: 'local-mesh[N]' for an N-device host-platform mesh, "
         "'tpu' for all attached TPU devices, 'multihost' for jax.distributed.")
    .str_conf("tpu")
)

DEFAULT_PARALLELISM = (
    ConfigBuilder("cyclone.default.parallelism")
    .doc("Default number of dataset partitions (0 = number of mesh devices).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(0)
)

BLOCK_SIZE_MAX_MEM = (
    ConfigBuilder("cyclone.dataset.blockSizeInMB")
    .doc("Max memory per instance block in MB "
         "(ref: ml/feature/Instance.scala:146 blokifyWithMaxMemUsage).")
    .float_conf(0.0)
)

AGGREGATION_DEPTH = (
    ConfigBuilder("cyclone.treeAggregate.depth")
    .doc("Depth of hierarchical reduction across DCN slices "
         "(ref: RDD.scala:1223 treeAggregate).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(2)
)

DEVICE_DTYPE = (
    ConfigBuilder("cyclone.compute.dtype")
    .doc("Accumulation dtype for device kernels; float32 keeps MXU throughput "
         "while matching JVM double loss curves to ~1e-6 relative.")
    .str_conf("float32")
)

DATA_DTYPE = (
    ConfigBuilder("cyclone.data.dtype")
    .doc("Storage dtype of the DATA tier — every materialized design "
         "matrix (dataset blocks, managed-tier spills, OvR label stacks). "
         "'auto' (default) is bfloat16 — fits are bandwidth-bound "
         "(BENCH r03-r05: 71% of the measured HBM streaming ceiling at "
         "0.096% MFU), so halving X's bytes halves the sweep — EXCEPT "
         "under jax x64 (the CPU parity/test config), where it resolves "
         "to float64 so reference-parity suites are untouched. All "
         "aggregators and kernels upcast to the float32 accumulator "
         "(cyclone.compute.dtype) inside the kernel; X is never "
         "materialized wider than this tier. 'float32' opts out and "
         "restores the pre-bf16 byte-identical sweep; 'float64' is only "
         "meaningful under x64 (silently canonicalized to f32 otherwise "
         "— graftlint JX004 polices that drift). Resolved when a dataset "
         "is materialized; mutable for the next dataset, not "
         "retroactively. The SECOND precision rung: 'auto8' resolves to "
         "float8_e4m3fn (1 byte, per-column scales at accumulator width, "
         "fp32 in-kernel accumulation) for fp8-capable estimators "
         "(LogisticRegression, LinearRegression l-bfgs) and to bfloat16 "
         "for everything else — except under x64, where it keeps the "
         "parity tier like 'auto'; 'float8' forces the same split through "
         "parity configs (the acceptance suites use it). fp8-capable fits "
         "carry a pre-fit envelope probe that falls back to bf16 (event "
         "PrecisionFallback + FitProfile.fp8_fallbacks) when e4m3's 3-bit "
         "mantissa would break the documented accuracy envelope — see "
         "docs/mixed-precision.md.")
    .check_value(lambda v: v in ("auto", "auto8", "bfloat16", "float8",
                                 "float32", "float64"),
                 "must be auto, auto8, bfloat16, float8, float32 or float64")
    .mutable()
    .str_conf("auto")
)

EVENT_LOG_ENABLED = (
    ConfigBuilder("cyclone.eventLog.enabled")
    .doc("Write the structured event journal to disk "
         "(ref: EventLoggingListener.scala:50).")
    .bool_conf(False)
)

EVENT_LOG_DIR = (
    ConfigBuilder("cyclone.eventLog.dir").doc("Event journal directory.").str_conf("/tmp/cyclone-events")
)

CHECKPOINT_DIR = (
    ConfigBuilder("cyclone.checkpoint.dir")
    .doc("Directory for dataset/optimizer checkpoints "
         "(ref: RDD.scala:1631 checkpoint).")
    .str_conf("")
)

HEARTBEAT_INTERVAL_MS = (
    ConfigBuilder("cyclone.executor.heartbeatInterval")
    .doc("Host-worker heartbeat interval in ms (ref: HeartbeatReceiver).")
    .int_conf(10000)
)

DRIVER_HEARTBEAT_ADDRESS = (
    ConfigBuilder("cyclone.driver.heartbeatAddress")
    .doc("host:port of the driver's HeartbeatServer. When set, this process "
         "runs a HeartbeatSender pinging it every "
         "cyclone.executor.heartbeatInterval ms — the over-the-wire worker "
         "liveness loop (ref: HeartbeatReceiver.scala:37). Empty = no "
         "cross-process heartbeats (single-host runs).")
    .str_conf("")
)

WORKER_ID = (
    ConfigBuilder("cyclone.worker.id")
    .doc("Identity reported in heartbeats; defaults to host:pid.")
    .str_conf("")
)

NETWORK_TIMEOUT_MS = (
    ConfigBuilder("cyclone.network.timeout")
    .doc("Control-plane RPC / worker-liveness timeout in ms. Must be well "
         "above the heartbeat interval or jitter expires healthy workers "
         "(the reference defaults to 120s vs a 10s heartbeat).")
    .int_conf(120000)
)

LBFGS_DEVICE_CHUNK = (
    ConfigBuilder("cyclone.ml.lbfgs.deviceChunk")
    .doc("L-BFGS iterations fused into one device dispatch for eligible "
         "fits (dense tier, standardized-or-no L2, no L1/bounds/"
         "checkpointing). 0 disables the chunked optimizer (host loop with "
         "fused line search, one dispatch per iteration).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(16)
)

USE_PALLAS_KERNELS = (
    ConfigBuilder("cyclone.ml.usePallasKernels")
    .doc("Route the eligible dense sweeps — binomial LogisticRegression "
         "(serial AND stacked), the LinearRegression l-bfgs objective, "
         "the RowMatrix Gramian and the KMeans assignment step — through "
         "the hand-written fused Pallas kernels (ops/kernels.py) instead "
         "of the XLA-fused jnp aggregators. 'auto' (default) makes the "
         "fused kernels the DEFAULT sweep on natively-lowered backends "
         "(TPU): one VMEM-resident row pass per loss/grad evaluation, "
         "narrow (bf16) blocks read at storage width with fp32 in-kernel "
         "accumulation, ~10-16% faster end-to-end at HBM scale "
         "(benchmarks/PALLAS_AB.md; small shapes are within relay noise "
         "either way). Everywhere else 'auto' keeps the XLA path — the "
         "interpreted kernels exist for tests, not speed. 'true'/'false' "
         "force one path for every eligible estimator.")
    .check_value(lambda v: str(v).lower() in ("auto", "true", "false"),
                 "must be auto, true or false")
    .str_conf("auto")
)

SHUFFLE_SPILL_ROW_BUDGET = (
    ConfigBuilder("cyclone.shuffle.spill.rowBudget")
    .doc("Values held in memory per host-shuffle bucket before spilling a "
         "sorted compressed run to disk (ref: ExternalAppendOnlyMap.scala:55 "
         "/ spark.shuffle.spill).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(1 << 20)
)

AUTH_SECRET = (
    ConfigBuilder("cyclone.authenticate.secret")
    .doc("Shared secret for the TCP fabric (exchange, deploy, heartbeats, "
         "SQL server): every connection performs a mutual HMAC-SHA256 "
         "challenge-response before any protocol byte (the role of "
         "spark.authenticate / SaslRpcHandler.java:44). Empty = open "
         "fabric. Spawned daemons inherit via CYCLONE_AUTH_SECRET.")
    .str_conf("")
)

SQL_WAREHOUSE_DIR = (
    ConfigBuilder("cyclone.sql.warehouse.dir")
    .doc("Warehouse directory for the PERSISTENT catalog (Spark's "
         "spark.sql.warehouse.dir; the metastore analog — "
         "HiveExternalCatalog.scala:56). When set, CREATE TABLE AS / "
         "INSERT INTO write table metadata + parquet parts here and "
         "survive process restart; empty = in-memory tables only.")
    .str_conf("")
)

ADAPTIVE_ENABLED = (
    ConfigBuilder("cyclone.sql.adaptive.enabled")
    .doc("Adaptive query execution over the exchange fabric: runtime size "
         "statistics pick broadcast joins and coalesce small shuffle "
         "output partitions (ref AdaptiveSparkPlanExec).")
    .bool_conf(True)
)

AUTO_BROADCAST_JOIN_THRESHOLD = (
    ConfigBuilder("cyclone.sql.autoBroadcastJoinThreshold")
    .doc("Max bytes for a join side to be broadcast to every process "
         "instead of hash-exchanging both sides (Spark's conf name and "
         "10 MB default; -1 disables).")
    .int_conf(10 * 1024 * 1024)
)

SKEW_JOIN_ENABLED = (
    ConfigBuilder("cyclone.sql.adaptive.skewJoin.enabled")
    .doc("AQE skew-join handling (Spark's conf name; ref "
         "OptimizeSkewedJoin.scala:55): a shuffle-join bucket whose "
         "byte estimate exceeds skewedPartitionFactor x the median AND "
         "skewedPartitionThresholdInBytes is SPLIT across processes — "
         "the splittable side's rows spread round-robin while the other "
         "side's rows for that bucket are duplicated everywhere.")
    .bool_conf(True)
)

SKEW_JOIN_FACTOR = (
    ConfigBuilder("cyclone.sql.adaptive.skewJoin.skewedPartitionFactor")
    .doc("A bucket is skew-eligible when its size exceeds this factor "
         "times the median bucket size (Spark's default 5).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(5)
)

SKEW_JOIN_THRESHOLD = (
    ConfigBuilder(
        "cyclone.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes")
    .doc("Minimum estimated bucket bytes before skew splitting applies "
         "(Spark's default 256m).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(256 * 1024 * 1024)
)

ADVISORY_PARTITION_BYTES = (
    ConfigBuilder("cyclone.sql.adaptive.advisoryPartitionSizeInBytes")
    .doc("Byte target for AQE post-shuffle coalescing (Spark's conf name "
         "and semantics; CoalesceShufflePartitions): adjacent small "
         "output partitions merge until their ESTIMATED bytes reach "
         "this. 0 falls back to the row-count target "
         "(advisoryPartitionRows).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(64 * 1024 * 1024)
)

ADVISORY_PARTITION_ROWS = (
    ConfigBuilder("cyclone.sql.adaptive.advisoryPartitionRows")
    .doc("Row-count FALLBACK for AQE post-shuffle coalescing, applied "
         "only when advisoryPartitionSizeInBytes is set to 0 — the byte "
         "target (Spark's semantics) takes precedence by default.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(1 << 16)
)

STORAGE_DEVICE_BUDGET = (
    ConfigBuilder("cyclone.storage.deviceBudget")
    .doc("Byte budget for DEVICE-tier managed datasets (context-owned "
         "StorageManager ≈ BlockManager memory store). Exceeding it "
         "demotes the least-recently-used managed dataset to the host "
         "tier. 0 = unbounded.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(0)
)

STORAGE_HOST_BUDGET = (
    ConfigBuilder("cyclone.storage.hostBudget")
    .doc("Byte budget for HOST-tier managed datasets; past it, LRU "
         "datasets demote to disk spill files. 0 = unbounded.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(0)
)

EXCHANGE_ADDRESSES = (
    ConfigBuilder("cyclone.exchange.addresses")
    .doc("Comma-separated host:port exchange endpoints, one per cooperating "
         "process, identical on every process. When set (with "
         "cyclone.exchange.rank), host-tier shuffles — "
         "PartitionedDataset.group_by_key/reduce_by_key and SQL "
         "Aggregate/Join — route cross-process through the HashExchange "
         "fabric (≈ ShuffleExchangeExec + block transfer); empty = "
         "single-process shuffles.")
    .str_conf("")
)

EXCHANGE_RANK = (
    ConfigBuilder("cyclone.exchange.rank")
    .doc("This process's index into cyclone.exchange.addresses.")
    .int_conf(-1)
)

EXCHANGE_NUM_BUCKETS = (
    ConfigBuilder("cyclone.exchange.numBuckets")
    .doc("Hash buckets per exchange round (≈ shuffle partitions; bucket b "
         "is owned by process b % n_processes).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(64)
)

TASK_MAX_FAILURES = (
    ConfigBuilder("cyclone.task.maxFailures")
    .doc("Retries per step before aborting (ref: TaskSetManager.scala:58).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(4)
)

MATMUL_PRECISION = (
    ConfigBuilder("cyclone.compute.matmulPrecision")
    .doc("Aggregator matmul precision: 'highest' (default) = multi-pass f32 "
         "on the MXU, matching the reference's f64 loss curves to ~1e-6; "
         "'default' = the backend's native (bf16-multiply) mode. Measured "
         "NEUTRAL for gemv-shaped binary aggregators on v5e (they are "
         "HBM-bound); only consider it for genuinely MXU-bound shapes "
         "(wide multinomial). Resolved when an aggregator is built.")
    .check_value(lambda v: v in ("highest", "default"),
                 "must be 'highest' or 'default'")
    .str_conf("highest")
)

METRICS_SINKS = (
    ConfigBuilder("cyclone.metrics.sinks")
    .doc("Comma-separated metric sinks: console, csv, prometheus "
         "(ref: metrics/MetricsSystem.scala:70 + conf/metrics.properties).")
    .str_conf("")
)

METRICS_PERIOD_S = (
    ConfigBuilder("cyclone.metrics.period")
    .doc("Push-sink report period in seconds (ref: CsvSink pollPeriod).")
    .float_conf(10.0)
)

METRICS_CSV_DIR = (
    ConfigBuilder("cyclone.metrics.csv.dir")
    .doc("Directory for the CSV metrics sink.")
    .str_conf("/tmp/cyclone-metrics")
)

PLUGINS = (
    ConfigBuilder("cyclone.plugins")
    .doc("Comma-separated plugin class paths loaded at context start "
         "(ref: api/plugin/SparkPlugin.java:37, spark.plugins).")
    .str_conf("")
)

PROMETHEUS_PORT = (
    ConfigBuilder("cyclone.metrics.prometheus.port")
    .doc("Port for the pull-based /metrics endpoint; 0 picks a free port "
         "(ref: PrometheusServlet.scala).")
    .int_conf(0)
)

MEMORY_BUDGET_FRACTION = (
    ConfigBuilder("cyclone.memory.budgetFraction")
    .doc("Compile-time memory budget guard: when a program's predicted "
         "peak HBM (XLA memory_analysis: arguments + outputs + "
         "temporaries + generated code, per device) exceeds this fraction "
         "of device memory, a MemoryBudgetExceeded event is posted and "
         "the chunked L-BFGS paths shrink deviceChunk proportionally "
         "instead of OOMing. Warn-only by default (see "
         "cyclone.memory.budgetAction). Scope: the chunked L-BFGS "
         "programs are guarded whenever this key is set explicitly or "
         "tracing is enabled; tree_aggregate and fused line-search "
         "programs are checked as part of the tracing harvest only — "
         "their untraced dispatch path stays one global read and never "
         "calls XLA's cost analysis.")
    .check_value(lambda v: 0 < v <= 1.0, "must be in (0, 1]")
    .float_conf(0.9)
)

MEMORY_BUDGET_ACTION = (
    ConfigBuilder("cyclone.memory.budgetAction")
    .doc("What an exceeded memory budget does beyond the event + chunk "
         "degradation: 'warn' (default) never raises; 'raise' throws "
         "MemoryBudgetError once degradation options are exhausted (the "
         "chunked L-BFGS guard degrades first and raises only if chunk 1 "
         "is still over budget; sites with nothing to degrade raise "
         "before dispatching the oversized program).")
    .check_value(lambda v: v in ("warn", "raise"),
                 "must be 'warn' or 'raise'")
    .str_conf("warn")
)

MEMORY_DEVICE_BYTES = (
    ConfigBuilder("cyclone.memory.deviceBytes")
    .doc("Per-device memory bytes the budget guard divides into. 0 (the "
         "default) auto-detects: device.memory_stats()['bytes_limit'] "
         "where the backend reports it (TPU/GPU), total host RAM for "
         "host-platform devices (CPU).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(0)
)

SERVING_MAX_BATCH = (
    ConfigBuilder("cyclone.serving.maxBatch")
    .doc("Upper bound on coalesced rows per serving dispatch. The model "
         "server AOT-compiles one predict program per power-of-two row "
         "bucket up to (the next power of two >=) this value at "
         "registration, so no request ever pays an XLA compile.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(64)
)

SERVING_WINDOW_MS = (
    ConfigBuilder("cyclone.serving.windowMs")
    .doc("Latency-bounded batching window in milliseconds (Clipper-style "
         "adaptive micro-batching): once a request is queued, the "
         "batcher waits at most this long for more requests to the same "
         "model before dispatching the coalesced batch. 0 dispatches "
         "immediately (no coalescing beyond what is already queued).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(5.0)
)

SERVING_DTYPE = (
    ConfigBuilder("cyclone.serving.dtype")
    .doc("Float dtype serving predict programs compute in. 'auto' (the "
         "default) resolves to the accumulator tier — float64 under jax "
         "x64, else float32. Request payloads and model parameters are "
         "cast to this width at the serving boundary; the bf16 data tier "
         "never applies to request batches (they are latency-, not "
         "bandwidth-bound, and scoring accuracy is part of the contract). "
         "'float64' requires jax x64 — without it the server downgrades "
         "to float32 with a warning rather than let XLA canonicalize f64 "
         "inputs to f32 silently.")
    .check_value(lambda v: v in ("auto", "float32", "float64"),
                 "must be 'auto', 'float32' or 'float64'")
    .str_conf("auto")
)

SERVING_MAX_QUEUE = (
    ConfigBuilder("cyclone.serving.maxQueue")
    .doc("Backpressure bound: maximum requests queued per registered "
         "model. Submissions past it fail fast with ServingOverloaded "
         "(503) instead of growing the queue without limit.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(1024)
)

SERVING_SHED_AFTER_MS = (
    ConfigBuilder("cyclone.serving.shedAfterMs")
    .doc("Admission-control patience: when the HBM budget guard predicts "
         "a dispatch would not fit (cyclone.memory.budgetFraction x "
         "device memory), the batch is re-queued and re-checked each "
         "batching window until its oldest request has waited this long, "
         "then every request in it is shed with ServingOverloaded (503). "
         "Serving never raises MemoryBudgetError and never dispatches a "
         "program the guard predicts will OOM.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(1000.0)
)

SERVING_MAX_RETRIES = (
    ConfigBuilder("cyclone.serving.maxRetries")
    .doc("Dispatch retries for TRANSIENT failures (resilience "
         "classification) before the batch is shed with a 5xx "
         "ServingError. Permanent failures shed immediately.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(3)
)

SERVING_QUANTIZE = (
    ConfigBuilder("cyclone.serving.quantize")
    .doc("Serve QUANTIZED predict programs: coefficient tensors stored "
         "fp8 (e4m3) with per-margin-row scales at serving dtype, "
         "dequantized inside the compiled kernel (one elementwise "
         "multiply — the per-row reduction stays independent of the "
         "batch dim, so bucket padding remains bitwise-neutral). Cuts "
         "each bucket program's parameter HBM ~4-8x, so the PR-5/PR-8 "
         "admission path fits strictly more gang models under the same "
         "cyclone.memory.budgetFraction. Margins round to e4m3's 3-bit "
         "mantissa (~6 percent relative per coefficient) — predictions at the "
         "decision boundary can flip; see docs/serving.md for the "
         "envelope. Off by default.")
    .bool_conf(False)
)

OOCORE_MODE = (
    ConfigBuilder("cyclone.oocore.mode")
    .doc("Out-of-core streaming fit mode (oocore/): 'auto' (default) keeps "
         "in-core fits but DEGRADES to the streaming epoch engine when the "
         "memory budget guard's chunk-halving bottoms out at deviceChunk=1 "
         "with the program still over budget (instead of warn/raise); "
         "'force' routes every eligible dense fit through the streaming "
         "path (each loss/grad evaluation is one double-buffered epoch "
         "over host shards); 'off' disables streaming entirely — the "
         "guard's pre-oocore warn/raise behavior applies.")
    .check_value(lambda v: v in ("auto", "force", "off"),
                 "must be auto, force or off")
    .mutable()
    .str_conf("auto")
)

OOCORE_SHARD_ROWS = (
    ConfigBuilder("cyclone.oocore.shardRows")
    .doc("Rows per out-of-core shard. Every shard is padded to ONE fixed "
         "(padRows, d) geometry (zero-weight padding rows, masked out of "
         "the psums), so a single compiled per-shard program serves the "
         "whole epoch; host staging peaks at O(shardRows · d), never "
         "O(n · d). Sized so one shard's device footprint is well under "
         "the memory budget while staying large enough that transfer "
         "latency amortizes (the double buffer hides it behind compute).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(65536)
)

OOCORE_PREFETCH_DEPTH = (
    ConfigBuilder("cyclone.oocore.prefetchDepth")
    .doc("Staged shards in flight ahead of compute (the pinned ring): 2 = "
         "classic double buffering — shard N+1's host read + h2d transfer "
         "overlaps shard N's compute. Device-resident shard copies are "
         "bounded by depth + 1; higher values only help when staging "
         "jitter exceeds one shard's compute time.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(2)
)

OOCORE_SHUFFLE = (
    ConfigBuilder("cyclone.oocore.shuffle")
    .doc("Shuffle shard ORDER per streamed-SGD epoch (seeded permutation "
         "keyed on the optimizer seed x step, so a fixed seed replays "
         "exactly). The epoch's accumulated gradient is order-invariant "
         "up to float summation order — parity against a fixed-order run "
         "is pinned — but staged shards hit the device in permuted order, "
         "the reference's sample-without-materialize story. Off keeps "
         "the fixed sequential order.")
    .bool_conf(False)
)

OOCORE_MAX_RETRIES = (
    ConfigBuilder("cyclone.oocore.maxRetries")
    .doc("Retries for a TRANSIENT shard-staging failure (resilience "
         "classification; seeded backoff) before the epoch aborts. "
         "Permanent failures abort immediately with the stream drained "
         "and the staging thread released — never a hang.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(3)
)

OOCORE_DIR = (
    ConfigBuilder("cyclone.oocore.dir")
    .doc("Directory for out-of-core shard files (npz, data-tier packed). "
         "Empty = the system temp dir. Shard sets built by the engine own "
         "their files and remove them on close/GC.")
    .str_conf("")
)

OOCORE_STREAM_DTYPE = (
    ConfigBuilder("cyclone.oocore.streamDtype")
    .doc("Storage dtype for out-of-core shards — the PRECISION RUNG of the "
         "host→device stream (docs/out-of-core.md 'Precision rungs'). "
         "'auto' (default) follows cyclone.data.dtype, including the fp8 "
         "tiers: under auto8/float8 the spill-time envelope probe "
         "(instance.fp8_probe_ok over the write-pass moments) decides "
         "fp8-vs-bf16 per shard SET — one geometry, one program — with "
         "the bf16 fallback surfaced as a PrecisionFallback event. "
         "'bfloat16' pins the bf16 rung; 'float8' requests e4m3 codes + "
         "per-column scales whenever the probe allows (the probe still "
         "gates — codes that would break the documented envelope fall "
         "back visibly, never silently).")
    .check_value(lambda v: v in ("auto", "bfloat16", "float8"),
                 "must be auto, bfloat16 or float8")
    .mutable()
    .str_conf("auto")
)

OOCORE_CACHE_BYTES = (
    ConfigBuilder("cyclone.oocore.cacheBytes")
    .doc("Byte bound for the shard-set reuse cache (oocore/cache.py): "
         "spilled shard sets are keyed by content hash (source dataset "
         "identity + stream tier + pad geometry), so CV folds, "
         "TrainValidationSplit and warm-start re-fits ATTACH to the "
         "existing spill instead of re-blocking and re-writing it — the "
         "second fit re-streams 0 spill-write bytes. LRU-evicted past the "
         "bound; live streams pin their entries (refcount), and every "
         "attach is integrity-checked per shard (sha256 — a corrupt entry "
         "is evicted and rebuilt, chaos-covered). 0 disables reuse.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .mutable()
    .int_conf(1 << 30)
)

TRACE_ENABLED = (
    ConfigBuilder("cyclone.trace.enabled")
    .doc("Enable step-level tracing (observe/): hierarchical spans over "
         "compile/dispatch/collective/transfer/checkpoint, per-fit "
         "FitProfiles in the status store, Chrome-trace export. Off by "
         "default; the disabled cost at every instrumentation site is one "
         "module-global read. The CYCLONE_TRACE env var (any truthy value) "
         "also enables it.")
    .bool_conf(False)
)

TRACE_DIR = (
    ConfigBuilder("cyclone.trace.dir")
    .doc("When set (and tracing is enabled), the context exports "
         "<dir>/<app_id>.trace.json — Chrome Trace Event Format, loadable "
         "in Perfetto — on stop().")
    .str_conf("")
)

TRACE_MAX_SPANS = (
    ConfigBuilder("cyclone.trace.maxSpans")
    .doc("Span buffer bound (a RING: past it the OLDEST span is dropped "
         "and counted — spans_dropped in the export header and "
         "FitProfile — so a long job always keeps its recent window).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(100_000)
)

FLIGHT_ENABLED = (
    ConfigBuilder("cyclone.telemetry.flight.enabled")
    .doc("Always-on flight recorder (observe/flight.py): when full "
         "tracing is off, the context installs a bounded ring of recent "
         "spans that records at near-zero cost (no XLA cost harvest, no "
         "metrics bridge — the trace_overhead BENCH field pins the "
         "number) and freezes/dumps its window on triggers: chaos fault "
         "firing, MeshSupervisor rebuild, serving shed, SLO breach. "
         "Dumps are written under cyclone.trace.dir when set; the last "
         "few stay readable in memory either way.")
    .bool_conf(True)
)

FLIGHT_RING_SPANS = (
    ConfigBuilder("cyclone.telemetry.flight.ringSpans")
    .doc("Flight-recorder ring size in spans — the window a triggered "
         "dump preserves.")
    .check_value(lambda v: v >= 16, "must be >= 16")
    .int_conf(2048)
)

FLIGHT_MIN_INTERVAL_MS = (
    ConfigBuilder("cyclone.telemetry.flight.minIntervalMs")
    .doc("Flight-dump throttle: triggers within this window of the "
         "previous dump only count, they do not re-dump (a shed burst "
         "freezes ONE window, not one per 503).")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(1000.0)
)

COLLECT_ADDRESS = (
    ConfigBuilder("cyclone.telemetry.collect.address")
    .doc("host:port of a TraceCollector (observe/collect.py). When set, "
         "the context enables tracing (if not already on), adopts the "
         "CYCLONE_TRACE_ID / CYCLONE_TRACE_PARENT distributed-trace "
         "context from the environment, and runs a SpanShipper that "
         "drains the span ring to the collector — deploy.submit_app "
         "seeds this (env conf channel) for every launched app when the "
         "submitting process runs a collector. Empty = no shipping.")
    .str_conf("")
)

COLLECT_INTERVAL_MS = (
    ConfigBuilder("cyclone.telemetry.collect.intervalMs")
    .doc("SpanShipper drain/ship period in milliseconds.")
    .check_value(lambda v: v > 0, "must be > 0")
    .float_conf(500.0)
)

COLLECT_MAX_BATCH = (
    ConfigBuilder("cyclone.telemetry.collect.maxBatch")
    .doc("Spans per shipped batch; an unreachable collector buffers up "
         "to 16x this, then drops oldest (drop-counted) — shipping never "
         "blocks a recording site.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(4096)
)

SKEW_ENABLED = (
    ConfigBuilder("cyclone.telemetry.skew.enabled")
    .doc("Online straggler/skew detection (observe/skew.py): rolling "
         "median + MAD over per-lane step times (out-of-core shard "
         "staging, serving model lanes, per-worker heartbeat RTT). "
         "Latched StragglerDetected / SloBreach events post to the "
         "listener bus (status store 'skew' list, /api/v1/skew, web UI) "
         "and to subscribers (MeshSupervisor.attach_skew — the elastic "
         "scheduler's mitigation input, ROADMAP item 4).")
    .bool_conf(True)
)

SKEW_WINDOW = (
    ConfigBuilder("cyclone.telemetry.skew.window")
    .doc("Rolling samples kept per (group, lane) for the skew medians.")
    .check_value(lambda v: v >= 4, "must be >= 4")
    .int_conf(64)
)

SKEW_MIN_SAMPLES = (
    ConfigBuilder("cyclone.telemetry.skew.minSamples")
    .doc("Samples a lane needs before it participates in straggler "
         "comparison — below it the detector stays silent (cold lanes "
         "must not convict or be convicted).")
    .check_value(lambda v: v >= 2, "must be >= 2")
    .int_conf(8)
)

SKEW_MAD_FACTOR = (
    ConfigBuilder("cyclone.telemetry.skew.madFactor")
    .doc("A lane is a straggler only when its rolling median exceeds the "
         "group median by this many MADs (AND by relFactor x the median "
         "— both gates must pass; see docs/observability.md tuning).")
    .check_value(lambda v: v > 0, "must be > 0")
    .float_conf(4.0)
)

SKEW_REL_FACTOR = (
    ConfigBuilder("cyclone.telemetry.skew.relFactor")
    .doc("Relative gate for straggler detection: the lane median must "
         "also exceed relFactor x the group median, so microscopic "
         "jitter in a tight group (MAD near 0) cannot convict.")
    .check_value(lambda v: v >= 1.0, "must be >= 1.0")
    .float_conf(1.5)
)

SKEW_MIN_GAP_MS = (
    ConfigBuilder("cyclone.telemetry.skew.minGapMs")
    .doc("Absolute-gap floor for straggler detection: a lane's rolling "
         "median must exceed the group median by at least this many "
         "milliseconds (on top of the MAD and relative gates). At "
         "millisecond step times benign jitter exceeds any relative "
         "factor; below this gap, mitigation could not pay for itself.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(10.0)
)

SLO_STEP_MS = (
    ConfigBuilder("cyclone.telemetry.slo.stepMs")
    .doc("Step-duration SLO in milliseconds for collective dispatches "
         "(group collectives.step): a sample over target fires ONE "
         "latched SloBreach event + a flight-recorder dump until a "
         "sample recovers. 0 disables.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(0.0)
)

SLO_SERVING_MS = (
    ConfigBuilder("cyclone.telemetry.slo.servingMs")
    .doc("Serving-dispatch SLO in milliseconds (group serving.dispatch); "
         "same latch/dump semantics as slo.stepMs. 0 disables.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(0.0)
)

USAGE_ENABLED = (
    ConfigBuilder("cyclone.usage.enabled")
    .doc("Per-job / per-tenant usage attribution (observe/attribution.py): "
         "work dispatched inside attribution.scope(job, tenant=...) "
         "charges device-seconds, FLOPs / bytes-accessed / HBM-peak "
         "(joined from the observe.costs registry), host->device staging "
         "bytes, serving requests / dispatch-seconds / sheds and "
         "supervisor/autoscaler actions to a bounded process-global "
         "UsageLedger. Periodic UsageReport events feed the status store "
         "(/api/v1/usage, web UI, history replay), labeled Prometheus "
         "gauges, and FitProfile.job_usage; per-host ledgers ride shipped "
         "span batches so the TraceCollector merges them cross-host. Off "
         "by default; the disabled cost at every instrumentation site is "
         "one module-global read (the usage BENCH block pins it).")
    .bool_conf(False)
)

USAGE_MAX_SCOPES = (
    ConfigBuilder("cyclone.usage.maxScopes")
    .doc("UsageLedger scope-row bound: past it the oldest scope folds "
         "into the '(evicted)' row (sums still match the totals row) and "
         "its labeled gauges unregister.")
    .check_value(lambda v: v >= 2, "must be >= 2")
    .int_conf(256)
)

USAGE_MAX_MODELS = (
    ConfigBuilder("cyclone.usage.maxModels")
    .doc("Per-scope serving model-table bound; overflow models share one "
         "'(other)' bucket.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(64)
)

USAGE_REPORT_INTERVAL_MS = (
    ConfigBuilder("cyclone.usage.reportIntervalMs")
    .doc("UsageReport / TelemetryStatsUpdated posting period in "
         "milliseconds. Reports carry CUMULATIVE snapshots, so the "
         "status store folds them by replacement and a lost report "
         "costs staleness, not data.")
    .check_value(lambda v: v > 0, "must be > 0")
    .float_conf(2000.0)
)


DOCTOR_RECOMPILE_MIN = (
    ConfigBuilder("cyclone.doctor.recompileMin")
    .doc("Recompile-storm conviction floor for observe/diagnose.py: the "
         "total number of EXCESS compile spans (beyond the first per "
         "program-cache identity) in the analyzed window before the "
         "doctor files a recompile-storm finding. The first compile of "
         "each program is warm-up, never evidence.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(2)
)

DOCTOR_TRANSFER_STALL_FRACTION = (
    ConfigBuilder("cyclone.doctor.transferStallFraction")
    .doc("Host-transfer stall threshold: non-streaming transfer-span "
         "seconds must reach this fraction of dispatch+collective "
         "seconds before the doctor convicts (the runtime twin of "
         "JX001's per-element device_get rule). oocore.* staging spans "
         "are excluded — streaming health is the overlap rule's job.")
    .check_value(lambda v: v > 0, "must be > 0")
    .float_conf(0.5)
)

DOCTOR_TRANSFER_MIN_COUNT = (
    ConfigBuilder("cyclone.doctor.transferMinCount")
    .doc("Minimum non-streaming transfer spans in the window before the "
         "transfer-stall rule may fire: one big final readback is a "
         "result fetch, not a stall pattern.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(8)
)

DOCTOR_OVERLAP_MIN = (
    ConfigBuilder("cyclone.doctor.overlapMin")
    .doc("Under-lapped-streaming threshold: the stage/compute overlap "
         "fraction (same interval math as scripts/bench_oocore.py) "
         "below which the doctor flags the double buffer as not "
         "hiding staging. Mirrors the bench gate's 0.30 floor.")
    .check_value(lambda v: 0.0 <= v <= 1.0, "must be in [0, 1]")
    .float_conf(0.30)
)

DOCTOR_MIN_STREAM_SPANS = (
    ConfigBuilder("cyclone.doctor.minStreamSpans")
    .doc("Minimum oocore.stage AND oocore.shard span count before the "
         "overlap rule judges a window; tiny streams have no steady "
         "state to measure.")
    .check_value(lambda v: v >= 2, "must be >= 2")
    .int_conf(8)
)

DOCTOR_SHED_MIN = (
    ConfigBuilder("cyclone.doctor.shedMin")
    .doc("Serving-pressure conviction floor: total shed requests in the "
         "serving stats snapshot at or above this files a finding.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(1)
)

DOCTOR_FALLBACK_MIN = (
    ConfigBuilder("cyclone.doctor.fallbackMin")
    .doc("Precision-envelope churn floor: precision.fallback events in "
         "the window at or above this files a finding (the fp8 "
         "envelope is re-proving itself instead of staying settled).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(1)
)

DOCTOR_ROOFLINE_FRACTION = (
    ConfigBuilder("cyclone.doctor.rooflineFraction")
    .doc("Roofline classification threshold: a profile at or above this "
         "fraction of its measured memory/compute ceiling is classified "
         "bandwidth- or compute-bound (by arithmetic intensity vs the "
         "ridge point); below it the fit is host-bound and the other "
         "rules explain why. Abstains when costs carry no peaks (CPU).")
    .check_value(lambda v: 0.0 < v <= 1.0, "must be in (0, 1]")
    .float_conf(0.5)
)

DOCTOR_FLIGHT_DIAGNOSIS = (
    ConfigBuilder("cyclone.doctor.flightDiagnosis")
    .doc("Auto-attach a DiagnosisReport to every flight-recorder dump: "
         "the doctor runs over the captured ring (spans only, no live "
         "sources) so a post-mortem dump arrives pre-triaged. Failures "
         "in the doctor never break the dump itself.")
    .bool_conf(True)
)

REGRESS_WINDOW = (
    ConfigBuilder("cyclone.regress.window")
    .doc("Bench-drift window: the newest row of each metric is judged "
         "against the median+MAD of up to this many preceding "
         "comparable rows in artifacts/bench_history.jsonl.")
    .check_value(lambda v: v >= 2, "must be >= 2")
    .int_conf(5)
)

REGRESS_MAD_FACTOR = (
    ConfigBuilder("cyclone.regress.madFactor")
    .doc("Robust drift threshold: a candidate beyond "
         "median +/- max(madFactor*MAD, relTol*median) in the bad "
         "direction is a regression; beyond it in the good direction "
         "is an improvement.")
    .check_value(lambda v: v > 0, "must be > 0")
    .float_conf(4.0)
)

REGRESS_REL_TOL = (
    ConfigBuilder("cyclone.regress.relTol")
    .doc("Relative floor under the MAD threshold: with a near-zero MAD "
         "(identical historical runs) drift under relTol*median still "
         "passes, so the gate never flags noise-free jitter.")
    .check_value(lambda v: v > 0, "must be > 0")
    .float_conf(0.05)
)

REGRESS_MIN_RUNS = (
    ConfigBuilder("cyclone.regress.minRuns")
    .doc("Minimum comparable history rows before a metric is gated; "
         "with fewer the verdict is insufficient-history (ok, never "
         "a nonzero exit).")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(3)
)


MULTIHOST_REPLICAS = (
    ConfigBuilder("cyclone.multihost.replicas")
    .doc("Replica (DCN) rows of the hierarchical mesh. 0 (default) is "
         "auto: one replica row per process, so every cross-process "
         "collective is confined to the replica axis and the data/model "
         "axes stay on ICI (multihost/hierarchy.py). An explicit value "
         "is honoured — with a warning when rows would straddle a "
         "process boundary.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(0)
)

MULTIHOST_MODEL_PARALLELISM = (
    ConfigBuilder("cyclone.multihost.modelParallelism")
    .doc("Model (feature-TP) axis width of the hierarchical mesh; stays "
         "inside one process's ICI domain.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(1)
)

MULTIHOST_CPU_COLLECTIVES = (
    ConfigBuilder("cyclone.multihost.cpuCollectives")
    .doc("Cross-process collectives implementation for CPU-backend "
         "multihost meshes (the 2-process smoke of the DCN hop): 'gloo' "
         "(default) enables real cross-process psums on XLA:CPU; 'none' "
         "leaves stock XLA behavior (multi-process CPU programs fail at "
         "dispatch). Ignored on TPU, whose fabric needs no helper.")
    .check_value(lambda v: v in ("gloo", "none"), "must be gloo or none")
    .str_conf("gloo")
)

MULTIHOST_BARRIER_TIMEOUT_MS = (
    ConfigBuilder("cyclone.multihost.barrierTimeoutMs")
    .doc("Teardown-barrier timeout in ms: context stop on a multihost "
         "mesh syncs every process at a coordination-service barrier "
         "before disconnecting (no process tears down the backend while "
         "a peer is mid-collective); a dead peer bounds the wait here.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(10000)
)

ELASTIC_MAX_RESHAPES = (
    ConfigBuilder("cyclone.elastic.maxReshapes")
    .doc("Planned mesh-shape changes (CapacityEvents) a MeshSupervisor "
         "applies before aborting with MeshDegradedError — the elastic "
         "twin of the max_rebuilds recovery budget, kept SEPARATE so a "
         "flapping autoscaler cannot eat the budget a real failure "
         "needs. Each reshape migrates cached datasets in memory, "
         "rebuilds the mesh at the event's master URL and resumes the "
         "fit in place from live optimizer state (no checkpoint "
         "round-trip); see docs/resilience.md 'Elasticity'.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(4)
)

ELASTIC_DRAIN_WINDOW_MS = (
    ConfigBuilder("cyclone.elastic.drainWindowMs")
    .doc("Default drain window for a preemption notice that names none: "
         "the in-memory optimizer-state handoff must complete within "
         "this budget of the notice for the rebuild to resume from the "
         "drained state; past it the handoff is DISCARDED and recovery "
         "falls back to the newest verifiable checkpoint — expired "
         "state is never silently resumed.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(5000)
)

ELASTIC_SPECULATION = (
    ConfigBuilder("cyclone.elastic.speculation")
    .doc("Arm Spark-style speculative re-dispatch for lanes with latched "
         "straggler verdicts (observe/skew.py -> supervisor.stragglers())"
         ": a convicted lane's next work runs with a duplicate copy — "
         "concurrent for host-side lanes (oocore shard staging), serial "
         "on the idle mesh for SPMD fit lanes — first result wins, the "
         "duplicate dedups bitwise. Off by default: speculation spends "
         "duplicate work, exactly as the reference's "
         "spark.speculation=false default does.")
    .mutable()
    .bool_conf(False)
)

AUTOSCALE_ENABLED = (
    ConfigBuilder("cyclone.autoscale.enabled")
    .doc("Arm the autoscaler control loop (elastic/autoscale.py): "
         "context.mesh_supervisor() starts a sampler thread that feeds "
         "skew/SLO/occupancy signals through the hysteresis policy and "
         "announces CapacityEvents on the elastic channel. Off by "
         "default: the control plane is opt-in, exactly as "
         "spark.dynamicAllocation.enabled=false is.")
    .bool_conf(False)
)

AUTOSCALE_TARGET_P99_MS = (
    ConfigBuilder("cyclone.autoscale.targetP99Ms")
    .doc("Serving p99 latency target in milliseconds, judged against "
         "the serving.dispatch timer histogram each tick: sustained "
         "breach (scaleUpAfterN consecutive ticks) votes scale-up. "
         "0 disables the serving leg; training pressure (stragglers, "
         "stepMs SLO) still drives the loop.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .float_conf(0.0)
)

AUTOSCALE_SCALE_UP_AFTER = (
    ConfigBuilder("cyclone.autoscale.scaleUpAfterN")
    .doc("Hysteresis window for growth: consecutive breached ticks "
         "(serving p99 over target, latched stragglers, or step-SLO "
         "latch) before ONE scale-up decision fires. Any healthy tick "
         "resets the streak — a flapping signal never reaches a "
         "verdict.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(3)
)

AUTOSCALE_SCALE_DOWN_AFTER = (
    ConfigBuilder("cyclone.autoscale.scaleDownAfterN")
    .doc("Hysteresis window for shrink: consecutive idle ticks "
         "(occupancy below the idle fraction with no breach) before a "
         "scale-down decision. Deliberately longer than scaleUpAfterN "
         "by default: shedding capacity too eagerly is the expensive "
         "mistake.")
    .check_value(lambda v: v >= 1, "must be >= 1")
    .int_conf(6)
)

AUTOSCALE_COOLDOWN_MS = (
    ConfigBuilder("cyclone.autoscale.cooldownMs")
    .doc("Per-direction cooldown after an applied decision, in LOGICAL "
         "milliseconds (Signals.t_ms — replay-stable): the same "
         "direction is suppressed until it elapses, so a persistent "
         "breach re-decides at a bounded rate instead of storming the "
         "reshape budget.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(30000)
)

AUTOSCALE_ACQUIRE_TIMEOUT_MS = (
    ConfigBuilder("cyclone.autoscale.acquireTimeoutMs")
    .doc("Bounded deadline for the scale-up capacity acquisition "
         "(parallel/allocation.acquire_devices): past it the decision "
         "degrades to a logged no-op + CapacityAcquired(ok=False) event "
         "and the train loop never wedges waiting on capacity that is "
         "not coming.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(5000)
)

AUTOSCALE_MAX_DECISIONS = (
    ConfigBuilder("cyclone.autoscale.maxDecisions")
    .doc("Applied-decision budget for one autoscaler life, SEPARATE "
         "from cyclone.elastic.maxReshapes: an exhausted policy "
         "degrades to one latched warn-hold decision and then holds — "
         "a misbehaving controller warns, it never thrashes the mesh "
         "or eats the reshape budget a real failure needs.")
    .check_value(lambda v: v >= 0, "must be >= 0")
    .int_conf(8)
)
