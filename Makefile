# Developer entry points. Tier-1 CI runs `make lint` (graftlint gate,
# also enforced by tests/test_graftlint.py) and `make test`.

.PHONY: lint lint-fast lint-json lint-sarif lint-ci test chaos obs-demo \
	bench bench-bytes bench-oocore bench-elastic serve-demo multihost \
	autoscale-sim usage-demo doctor doctor-demo bench-regress

# the full interprocedural pass (JX001-JX019, concurrency + abstract
# shape/sharding rules included); fails on any finding not grandfathered
# in baseline.json (which a PR may shrink, never grow). The tail line
# prints the top-3 slowest rules so rule authors see their cost.
lint:
	python -m cycloneml_tpu.analysis cycloneml_tpu \
	    --baseline cycloneml_tpu/analysis/baseline.json

# incremental gate for the edit loop: full call-graph facts, but checks
# and reports only files changed per `git diff` plus their (transitive)
# callers' modules (parse cache reused)
lint-fast:
	python -m cycloneml_tpu.analysis --changed \
	    --baseline cycloneml_tpu/analysis/baseline.json

lint-json:
	python -m cycloneml_tpu.analysis cycloneml_tpu \
	    --baseline cycloneml_tpu/analysis/baseline.json --json

# SARIF 2.1.0 for CI/code-review inline rendering
lint-sarif:
	python -m cycloneml_tpu.analysis cycloneml_tpu \
	    --baseline cycloneml_tpu/analysis/baseline.json --sarif

# the CI job: full run, SARIF artifact at a stable path
# (artifacts/graftlint.sarif; override GRAFTLINT_SARIF_OUT), parse cache
# relocatable via CYCLONE_LINT_CACHE, nonzero exit on any unsuppressed
# finding
lint-ci:
	bash scripts/ci_lint.sh

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider

chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
	    -p no:cacheprovider

# the 2-process deploy/multihost harness standalone: real Master/Worker
# daemons, real jax.distributed rendezvous, the kill-a-worker recovery
# loop. Hard timeout: a wedged cross-process rendezvous must kill the
# run loudly, never hang CI.
multihost:
	timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
	    tests/test_multihost.py tests/test_deploy.py -q \
	    -p no:cacheprovider

# small traced fit -> exported Chrome trace -> schema + profile validation
obs-demo:
	JAX_PLATFORMS=cpu python scripts/obs_demo.py

# usage-attribution acceptance: two scoped jobs (a fit + a serving
# storm), per-scope device-seconds/FLOPs/bytes must sum to the global
# ledger within 1% and /api/v1/usage must serve both rows
usage-demo:
	JAX_PLATFORMS=cpu python scripts/usage_demo.py

# one JSON line: e2e LR throughput + phases + the multi-class OvR
# stacked-vs-serial comparison (ovr_stacked_speedup, models_per_compile).
# Tee'd to artifacts/ so `make bench bench-regress` gates the run it made.
bench:
	@mkdir -p artifacts
	python bench.py | tee artifacts/bench_last.json

# regression sentinel: backfill BENCH_r*.json into the append-only
# artifacts/bench_history.jsonl ledger, ingest artifacts/bench_last.json
# if present, judge each metric's newest row against median+MAD of its
# comparable history (cyclone.regress.*) — nonzero on any regression.
# Self-test of the gate itself: `... bench_regress.py --inject-regression`
bench-regress:
	python scripts/bench_regress.py --ingest artifacts/bench_last.json

# offline bottleneck diagnosis over a Chrome trace or flight dump:
# make doctor TRACE=artifacts/trace.json — exit 2 when anything fires
doctor:
	python -m cycloneml_tpu.observe.doctor $(TRACE)

# performance-doctor acceptance: clean warm fit => ZERO findings,
# pathological fit (forced recompiles + delayed staging lane + 1-byte
# shard cache) => >= 4 distinct evidence-backed finding kinds, and the
# doctor CLI --json byte-identical across two runs over the same trace
doctor-demo:
	JAX_PLATFORMS=cpu python scripts/doctor_demo.py

# standalone sweep-byte check, BOTH narrow legs: the bf16 data-tier
# sweep must access < 60% of the fp32 sweep's bytes and the fp8 (e4m3)
# sweep < 45% (measured ~0.35 at n=4096 d=256) — XLA cost-analysis
# ground truth, lower-only
bench-bytes:
	python scripts/bench_bytes.py

# out-of-core acceptance: streamed vs in-core wall time, epoch sweep
# bytes + O(shard) peak via costs.streamed_sweep_cost, and the
# transfer/compute overlap fraction from the stream spans — exits
# nonzero if overlap < 30% on the 8-device CPU smoke
bench-oocore:
	python scripts/bench_oocore.py

# elastic acceptance: time-to-resume for the same full->half mesh
# transition, reshard-in-place (memory) vs checkpoint round-trip
# (disk + sha256) on the 8-device CPU smoke — exits nonzero unless the
# reshard path is strictly faster
bench-elastic:
	python scripts/bench_elastic.py

# serving acceptance demo: 2 models, concurrent request storm, asserts
# compile-count == bucket-count and p99 under the window bound
serve-demo:
	JAX_PLATFORMS=cpu python scripts/serve_demo.py

# autoscale control-plane gate: replay the committed signal trace
# through the production policy twice — byte-identical logs
# (determinism) AND byte-equal to the committed golden (drift). A diff
# here IS the policy-change review artifact; regenerate deliberately
# with `python scripts/autoscale_sim.py --update`. Pure host-side, <1s.
autoscale-sim:
	python scripts/autoscale_sim.py
